"""TPC-H correctness tests against independent pandas oracles.

The reference's integration strategy runs q1,3,5,6,10,12 and eyeballs output
(docs/integration-testing.md, rust/benchmarks/tpch/run.sh:5-8); here the same
set (plus decorrelation-heavy queries) is asserted programmatically against
pandas re-implementations on the same generated data.
"""

import pathlib

import numpy as np
import pandas as pd
import pyarrow.parquet as pq
import pytest

from ballista_tpu.engine import ExecutionContext
from benchmarks.tpch.datagen import generate, register_all

QUERIES = pathlib.Path(__file__).parent.parent / "benchmarks" / "tpch" / "queries"


@pytest.fixture(scope="session")
def tpch_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("tpch")
    generate(str(d), sf=0.005, parts=2)
    return str(d)


@pytest.fixture(scope="session")
def tables(tpch_dir):
    names = ["lineitem", "orders", "customer", "supplier", "nation", "region",
             "part", "partsupp"]
    return {t: pq.read_table(f"{tpch_dir}/{t}").to_pandas() for t in names}


@pytest.fixture()
def ctx(tpch_dir):
    c = ExecutionContext()
    register_all(c, tpch_dir)
    return c


def run(ctx, name):
    sql = (QUERIES / f"{name}.sql").read_text()
    return ctx.sql(sql).collect().to_pandas()


def assert_frames_close(got: pd.DataFrame, want: pd.DataFrame):
    assert len(got) == len(want), f"row count {len(got)} != {len(want)}"
    assert list(got.columns) == list(want.columns), (got.columns, want.columns)
    for c in want.columns:
        g, w = got[c].to_numpy(), want[c].to_numpy()
        if np.issubdtype(w.dtype, np.floating):
            np.testing.assert_allclose(g.astype(float), w.astype(float), rtol=1e-9)
        else:
            assert list(g) == list(w), f"column {c}: {g[:5]} != {w[:5]}"


def test_q1(ctx, tables):
    got = run(ctx, "q1")
    li = tables["lineitem"]
    d = li[li.l_shipdate <= pd.Timestamp("1998-09-02").date()]
    disc = d.l_extendedprice * (1 - d.l_discount)
    w = (
        d.assign(disc_price=disc, charge=disc * (1 + d.l_tax))
        .groupby(["l_returnflag", "l_linestatus"], as_index=False)
        .agg(
            sum_qty=("l_quantity", "sum"),
            sum_base_price=("l_extendedprice", "sum"),
            sum_disc_price=("disc_price", "sum"),
            sum_charge=("charge", "sum"),
            avg_qty=("l_quantity", "mean"),
            avg_price=("l_extendedprice", "mean"),
            avg_disc=("l_discount", "mean"),
            count_order=("l_quantity", "size"),
        )
        .sort_values(["l_returnflag", "l_linestatus"])
        .reset_index(drop=True)
    )
    assert_frames_close(got, w)


def test_q3(ctx, tables):
    got = run(ctx, "q3")
    c, o, li = tables["customer"], tables["orders"], tables["lineitem"]
    cut = pd.Timestamp("1995-03-15").date()
    j = (
        c[c.c_mktsegment == "BUILDING"]
        .merge(o[o.o_orderdate < cut], left_on="c_custkey", right_on="o_custkey")
        .merge(li[li.l_shipdate > cut], left_on="o_orderkey", right_on="l_orderkey")
    )
    j = j.assign(rev=j.l_extendedprice * (1 - j.l_discount))
    w = (
        j.groupby(["l_orderkey", "o_orderdate", "o_shippriority"], as_index=False)
        .agg(revenue=("rev", "sum"))
        [["l_orderkey", "revenue", "o_orderdate", "o_shippriority"]]
        .sort_values(["revenue", "o_orderdate"], ascending=[False, True])
        .head(10)
        .reset_index(drop=True)
    )
    assert_frames_close(got, w)


def test_q5(ctx, tables):
    got = run(ctx, "q5")
    t = tables
    lo = pd.Timestamp("1994-01-01").date()
    hi = pd.Timestamp("1995-01-01").date()
    j = (
        t["customer"]
        .merge(t["orders"], left_on="c_custkey", right_on="o_custkey")
        .merge(t["lineitem"], left_on="o_orderkey", right_on="l_orderkey")
        .merge(t["supplier"], left_on="l_suppkey", right_on="s_suppkey")
        .merge(t["nation"], left_on="s_nationkey", right_on="n_nationkey")
        .merge(t["region"], left_on="n_regionkey", right_on="r_regionkey")
    )
    j = j[
        (j.c_nationkey == j.s_nationkey)
        & (j.r_name == "ASIA")
        & (j.o_orderdate >= lo)
        & (j.o_orderdate < hi)
    ]
    j = j.assign(rev=j.l_extendedprice * (1 - j.l_discount))
    w = (
        j.groupby("n_name", as_index=False)
        .agg(revenue=("rev", "sum"))
        .sort_values("revenue", ascending=False)
        .reset_index(drop=True)
    )
    assert_frames_close(got, w)


def test_q6(ctx, tables):
    got = run(ctx, "q6")
    li = tables["lineitem"]
    lo = pd.Timestamp("1994-01-01").date()
    hi = pd.Timestamp("1995-01-01").date()
    d = li[
        (li.l_shipdate >= lo)
        & (li.l_shipdate < hi)
        & (li.l_discount >= 0.05)
        & (li.l_discount <= 0.07)
        & (li.l_quantity < 24)
    ]
    want = (d.l_extendedprice * d.l_discount).sum()
    assert got["revenue"][0] == pytest.approx(want, rel=1e-9)


def test_q4_exists_decorrelation(ctx, tables):
    got = run(ctx, "q4")
    o, li = tables["orders"], tables["lineitem"]
    lo = pd.Timestamp("1993-07-01").date()
    hi = pd.Timestamp("1993-10-01").date()
    ok = li[li.l_commitdate < li.l_receiptdate].l_orderkey.unique()
    d = o[(o.o_orderdate >= lo) & (o.o_orderdate < hi) & o.o_orderkey.isin(ok)]
    w = (
        d.groupby("o_orderpriority", as_index=False)
        .agg(order_count=("o_orderkey", "size"))
        .sort_values("o_orderpriority")
        .reset_index(drop=True)
    )
    assert_frames_close(got, w)


def test_q10(ctx, tables):
    got = run(ctx, "q10")
    t = tables
    lo = pd.Timestamp("1993-10-01").date()
    hi = pd.Timestamp("1994-01-01").date()
    j = (
        t["customer"]
        .merge(t["orders"], left_on="c_custkey", right_on="o_custkey")
        .merge(t["lineitem"], left_on="o_orderkey", right_on="l_orderkey")
        .merge(t["nation"], left_on="c_nationkey", right_on="n_nationkey")
    )
    j = j[(j.o_orderdate >= lo) & (j.o_orderdate < hi) & (j.l_returnflag == "R")]
    j = j.assign(rev=j.l_extendedprice * (1 - j.l_discount))
    w = (
        j.groupby(
            ["c_custkey", "c_name", "c_acctbal", "c_phone", "n_name",
             "c_address", "c_comment"],
            as_index=False,
        )
        .agg(revenue=("rev", "sum"))
        [["c_custkey", "c_name", "revenue", "c_acctbal", "n_name", "c_address",
          "c_phone", "c_comment"]]
        .sort_values("revenue", ascending=False)
        .head(20)
        .reset_index(drop=True)
    )
    assert_frames_close(got, w)


def test_q12(ctx, tables):
    got = run(ctx, "q12")
    o, li = tables["orders"], tables["lineitem"]
    lo = pd.Timestamp("1994-01-01").date()
    hi = pd.Timestamp("1995-01-01").date()
    j = o.merge(li, left_on="o_orderkey", right_on="l_orderkey")
    j = j[
        j.l_shipmode.isin(["MAIL", "SHIP"])
        & (j.l_commitdate < j.l_receiptdate)
        & (j.l_shipdate < j.l_commitdate)
        & (j.l_receiptdate >= lo)
        & (j.l_receiptdate < hi)
    ]
    high = j.o_orderpriority.isin(["1-URGENT", "2-HIGH"]).astype(int)
    w = (
        j.assign(h=high, l=1 - high)
        .groupby("l_shipmode", as_index=False)
        .agg(high_line_count=("h", "sum"), low_line_count=("l", "sum"))
        .sort_values("l_shipmode")
        .reset_index(drop=True)
    )
    assert_frames_close(got, w)


def test_q14_case_join(ctx, tables):
    got = run(ctx, "q14")
    li, p = tables["lineitem"], tables["part"]
    lo = pd.Timestamp("1995-09-01").date()
    hi = pd.Timestamp("1995-10-01").date()
    j = li[(li.l_shipdate >= lo) & (li.l_shipdate < hi)].merge(
        p, left_on="l_partkey", right_on="p_partkey"
    )
    rev = j.l_extendedprice * (1 - j.l_discount)
    promo = rev.where(j.p_type.str.startswith("PROMO"), 0.0).sum()
    want = 100.0 * promo / rev.sum()
    assert got["promo_revenue"][0] == pytest.approx(want, rel=1e-9)


def test_q17_correlated_scalar(ctx, tables):
    got = run(ctx, "q17")
    li, p = tables["lineitem"], tables["part"]
    sel = p[(p.p_brand == "Brand#23") & (p.p_container == "MED BOX")]
    j = li.merge(sel, left_on="l_partkey", right_on="p_partkey")
    avg_by_part = li.groupby("l_partkey").l_quantity.mean()
    thresh = j.l_partkey.map(avg_by_part) * 0.2
    want = j[j.l_quantity < thresh].l_extendedprice.sum() / 7.0
    if np.isnan(want):
        assert got["avg_yearly"][0] is None or np.isnan(got["avg_yearly"][0])
    else:
        assert got["avg_yearly"][0] == pytest.approx(want, rel=1e-9)


def test_q19_disjunctive_join(ctx, tables):
    got = run(ctx, "q19")
    li, p = tables["lineitem"], tables["part"]
    j = li.merge(p, left_on="l_partkey", right_on="p_partkey")
    c1 = (
        (j.p_brand == "Brand#12")
        & j.p_container.isin(["SM CASE", "SM BOX", "SM PACK", "SM PKG"])
        & (j.l_quantity >= 1) & (j.l_quantity <= 11)
        & (j.p_size >= 1) & (j.p_size <= 5)
    )
    c2 = (
        (j.p_brand == "Brand#23")
        & j.p_container.isin(["MED BAG", "MED BOX", "MED PKG", "MED PACK"])
        & (j.l_quantity >= 10) & (j.l_quantity <= 20)
        & (j.p_size >= 1) & (j.p_size <= 10)
    )
    c3 = (
        (j.p_brand == "Brand#34")
        & j.p_container.isin(["LG CASE", "LG BOX", "LG PACK", "LG PKG"])
        & (j.l_quantity >= 20) & (j.l_quantity <= 30)
        & (j.p_size >= 1) & (j.p_size <= 15)
    )
    common = j.l_shipmode.isin(["AIR", "AIR REG"]) & (
        j.l_shipinstruct == "DELIVER IN PERSON"
    )
    d = j[(c1 | c2 | c3) & common]
    want = (d.l_extendedprice * (1 - d.l_discount)).sum()
    val = got["revenue"][0]
    if want == 0:
        assert val is None or val == 0 or np.isnan(val)
    else:
        assert val == pytest.approx(want, rel=1e-9)


def test_q22_anti_join_substring(ctx, tables):
    got = run(ctx, "q22")
    c, o = tables["customer"], tables["orders"]
    codes = ["13", "31", "23", "29", "30", "18", "17"]
    cc = c.assign(cntrycode=c.c_phone.str[:2])
    sel = cc[cc.cntrycode.isin(codes)]
    avg_bal = sel[sel.c_acctbal > 0.0].c_acctbal.mean()
    no_orders = ~sel.c_custkey.isin(o.o_custkey.unique())
    d = sel[(sel.c_acctbal > avg_bal) & no_orders]
    w = (
        d.groupby("cntrycode", as_index=False)
        .agg(numcust=("c_custkey", "size"), totacctbal=("c_acctbal", "sum"))
        .sort_values("cntrycode")
        .reset_index(drop=True)
    )
    assert_frames_close(got, w)


def test_all_queries_execute(ctx):
    for i in range(1, 23):
        out = run(ctx, f"q{i}")
        assert out is not None

"""Scheduler crash tolerance (ISSUE 6): durable assignment ledger,
crash-safe (atomic) planning writes, and restart reconciliation.

The acceptance run kills the scheduler mid-job (seeded `scheduler.crash`
chaos, keyed on the accepted-status sequence rotated by the restart
generation), restarts a FRESH SchedulerServer on the same SqliteBackend
store, and asserts the job completes bit-identical to the fault-free run —
without re-executing any task an executor still owned (task_retry and
orphan_reassigned stay 0). Torn planning is pinned write-by-write: a crash
between any pair of planning keys leaves NO torn job visible to clients or
assignment, because planning publishes through one atomic put_all whose
commit marker is the queued->running job-status flip."""

import threading
import time

import pyarrow as pa
import pytest

from ballista_tpu.config import BallistaConfig
from ballista_tpu.proto import ballista_pb2 as pb
from ballista_tpu.scheduler.kv import MemoryBackend, SqliteBackend
from ballista_tpu.scheduler.state import SchedulerState
from ballista_tpu.utils.chaos import ChaosInjected, ChaosInjector

# -- durable assignment ledger ----------------------------------------------


def _running_job(s, job="j"):
    running = pb.JobStatus()
    running.running.SetInParent()
    s.save_job_metadata(job, running)


def _meta(i):
    return pb.ExecutorMetadata(id=i, host="h", port=1)


def _pending(job, stage, part, attempt=0):
    t = pb.TaskStatus()
    t.partition_id.job_id = job
    t.partition_id.stage_id = stage
    t.partition_id.partition_id = part
    t.attempt = attempt
    return t


def _stage_plan(s, job="j", stage=1):
    from ballista_tpu.physical.basic import EmptyExec

    s.save_stage_plan(job, stage, EmptyExec(True, pa.schema([("a", pa.int64())])))


def _echo(job, stage, part, attempt):
    e = pb.RunningTaskEcho()
    e.partition_id.job_id = job
    e.partition_id.stage_id = stage
    e.partition_id.partition_id = part
    e.attempt = attempt
    return e


def test_assignment_is_written_through_to_the_kv(tmp_path):
    db = str(tmp_path / "state.db")
    s = SchedulerState(SqliteBackend(db), "t")
    _running_job(s)
    s.save_executor_metadata(_meta("e1"))
    _stage_plan(s)
    s.save_task_status(_pending("j", 1, 0))
    assert s.assign_next_schedulable_task("e1") is not None
    raw = s.kv.get("/ballista/t/assignments/j/1/0")
    assert raw is not None
    a = pb.Assignment()
    a.ParseFromString(raw)
    assert a.executor_id == "e1" and a.attempt == 0
    # resolving the task clears the durable entry
    done = pb.TaskStatus()
    done.partition_id.CopyFrom(_pending("j", 1, 0).partition_id)
    done.completed.executor_id = "e1"
    done.completed.path = "/x"
    assert s.accept_task_status(done)
    assert s.kv.get("/ballista/t/assignments/j/1/0") is None


def test_restarted_scheduler_readopts_echoed_assignment(tmp_path):
    """The re-adoption path: a fresh SchedulerState on the same store
    reloads the ledger; the owner's attempt-matching echo confirms the
    task (restart_readopted), which is NOT re-executed."""
    from ballista_tpu.ops.runtime import recovery_stats

    db = str(tmp_path / "state.db")
    s1 = SchedulerState(SqliteBackend(db), "t")
    _running_job(s1)
    s1.save_executor_metadata(_meta("e1"))
    _stage_plan(s1)
    s1.save_task_status(_pending("j", 1, 0))
    assert s1.assign_next_schedulable_task("e1") is not None
    del s1  # crash

    recovery_stats(reset=True)
    s2 = SchedulerState(SqliteBackend(db), "t")
    stats = s2.recover()
    assert stats.get("scheduler_restart") == 1
    assert stats.get("restart_assignment_restored") == 1
    assert stats.get("restart_job_resumed") == 1
    assert ("j", 1, 0) in s2._assigned
    # the owner vouches with the matching attempt: re-adopted, not requeued
    assert s2.reconcile_running_tasks("e1", [_echo("j", 1, 0, 0)]) == 0
    assert s2.get_task_status("j", 1, 0).WhichOneof("status") == "running"
    assert ("j", 1, 0) not in s2._assigned
    assert s2.kv.get("/ballista/t/assignments/j/1/0") is None
    assert recovery_stats().get("restart_readopted", 0) == 1


def test_restarted_scheduler_requeues_unvouched_assignment(tmp_path):
    """Nobody echoes the reloaded entry within the grace window: the task
    requeues through the normal retry path (fresh attempt + history)."""
    import ballista_tpu.scheduler.state as state_mod

    db = str(tmp_path / "state.db")
    s1 = SchedulerState(SqliteBackend(db), "t")
    _running_job(s1)
    s1.save_executor_metadata(_meta("e1"))
    _stage_plan(s1)
    s1.save_task_status(_pending("j", 1, 0))
    assert s1.assign_next_schedulable_task("e1") is not None
    del s1

    s2 = SchedulerState(SqliteBackend(db), "t")
    s2.recover()
    old = state_mod.ORPHANED_ASSIGNMENT_GRACE_SECS
    state_mod.ORPHANED_ASSIGNMENT_GRACE_SECS = 0.0
    try:
        assert s2.reconcile_running_tasks("e1", []) == 1
    finally:
        state_mod.ORPHANED_ASSIGNMENT_GRACE_SECS = old
    t = s2.get_task_status("j", 1, 0)
    assert t.WhichOneof("status") is None and t.attempt == 1
    assert s2.kv.get("/ballista/t/assignments/j/1/0") is None


def test_stale_attempt_echo_does_not_vouch(tmp_path):
    """An executor still running a SUPERSEDED attempt cannot re-adopt the
    current one: its echo names the old attempt and is ignored."""
    import ballista_tpu.scheduler.state as state_mod

    db = str(tmp_path / "state.db")
    s1 = SchedulerState(SqliteBackend(db), "t")
    _running_job(s1)
    s1.save_executor_metadata(_meta("e1"))
    _stage_plan(s1)
    s1.save_task_status(_pending("j", 1, 0, attempt=2))
    status, _ = s1.assign_next_schedulable_task("e1")
    assert status.attempt == 2
    del s1

    s2 = SchedulerState(SqliteBackend(db), "t")
    s2.recover()
    old = state_mod.ORPHANED_ASSIGNMENT_GRACE_SECS
    state_mod.ORPHANED_ASSIGNMENT_GRACE_SECS = 0.0
    try:
        # attempt-0 echo for an attempt-2 ledger entry: requeued anyway
        assert s2.reconcile_running_tasks("e1", [_echo("j", 1, 0, 0)]) == 1
    finally:
        state_mod.ORPHANED_ASSIGNMENT_GRACE_SECS = old
    assert s2.get_task_status("j", 1, 0).attempt == 3


def test_recover_drops_resolved_ledger_entries(tmp_path):
    """Ledger entries whose task resolved (or was superseded) before the
    crash are discarded on reload, not resurrected."""
    db = str(tmp_path / "state.db")
    s1 = SchedulerState(SqliteBackend(db), "t")
    _running_job(s1)
    s1.save_executor_metadata(_meta("e1"))
    _stage_plan(s1)
    s1.save_task_status(_pending("j", 1, 0))
    assert s1.assign_next_schedulable_task("e1") is not None
    # simulate: the completion wrote but the crash hit before the ledger
    # delete — replay must treat the entry as resolved
    done = pb.TaskStatus()
    done.partition_id.CopyFrom(_pending("j", 1, 0).partition_id)
    done.completed.executor_id = "e1"
    done.completed.path = "/x"
    s1.save_task_status(done)  # raw write, ledger entry left behind
    del s1

    s2 = SchedulerState(SqliteBackend(db), "t")
    s2.recover()
    assert s2._assigned == {}
    assert s2.kv.get("/ballista/t/assignments/j/1/0") is None


# -- crash-safe planning writes ---------------------------------------------


class _CrashAtWrite:
    """Chaos stub that raises on the k-th staged planning write — the
    'crash between each pair of planning keys' probe. Duck-types the one
    injector method the planning path uses. The lease mint (ISSUE 20)
    rides the same commit and counts as one more seam: crashing there
    must be just as invisible as crashing between any other pair."""

    def __init__(self, k):
        self.k = k
        self.calls = 0

    def maybe_fail(self, site, key):
        assert site in ("scheduler.plan_write", "kv.lease")
        self.calls += 1
        if self.calls == self.k:
            raise ChaosInjected(site, key)


def _submit_sales_job(server, n_parts=2):
    from ballista_tpu.logical import col, functions as F
    from ballista_tpu.serde.logical import plan_to_proto
    from ballista_tpu.engine.context import ExecutionContext

    ctx = ExecutionContext()
    ctx.register_record_batches(
        "t", pa.table({"g": ["a", "b", "a", "b"], "v": [1.0, 2.0, 3.0, 4.0]}),
        n_partitions=n_parts,
    )
    df = ctx.table("t").aggregate([col("g")], [F.sum(col("v")).alias("s")])
    params = pb.ExecuteQueryParams()
    params.logical_plan.CopyFrom(plan_to_proto(df.logical_plan()))
    return server.ExecuteQuery(params).job_id


def test_torn_planning_write_leaves_no_job_state_visible(tmp_path):
    """Crash at EVERY staged planning write in turn: the job must stay
    queued with zero planning keys (stages, tasks) visible — the atomic
    put_all never ran — and assignment must hand out nothing."""
    from ballista_tpu.scheduler.server import SchedulerServer

    db = str(tmp_path / "state.db")
    server = SchedulerServer(
        SqliteBackend(db), namespace="t", synchronous_planning=True
    )
    # count the staged writes of an identical healthy plan first
    probe = _CrashAtWrite(k=10**9)
    server.state._chaos = probe
    job_ok = _submit_sales_job(server)
    total_writes = probe.calls
    assert total_writes >= 3  # stage plan(s) + tasks + commit

    for k in range(1, total_writes + 1):
        server.state._chaos = _CrashAtWrite(k)
        with pytest.raises(ChaosInjected):
            _submit_sales_job(server)
        server.state._chaos = None
        # exactly one job planned successfully (the probe); every torn
        # submission left nothing but its queued marker + settings
        tasks = server.state.get_all_tasks()
        assert {t.partition_id.job_id for t in tasks} == {job_ok}
        stage_keys = [
            key for key, _ in server.state.kv.get_prefix("/ballista/t/stages")
        ]
        assert all(f"/{job_ok}/" in key for key in stage_keys)
        torn = [
            key.rsplit("/", 1)[1]
            for key, _ in server.state.kv.get_prefix("/ballista/t/jobs")
        ]
        for job_id in torn:
            if job_id == job_ok:
                continue
            js = server.state.get_job_metadata(job_id)
            assert js.WhichOneof("status") == "queued"
            server.state.synchronize_job_status(job_id)  # must not touch it
            assert server.state.get_job_metadata(job_id).WhichOneof("status") == "queued"
        # nothing assignable beyond the probe job's own tasks
        assigned = server.state.assign_next_schedulable_task("eX")
        if assigned is not None:
            assert assigned[0].partition_id.job_id == job_ok


def test_recover_fails_torn_jobs_cleanly(tmp_path):
    """A restarted scheduler turns uncommitted (queued) jobs into clean
    failures — the client gets 'resubmit', never a hang or a torn run."""
    from ballista_tpu.scheduler.server import SchedulerServer

    db = str(tmp_path / "state.db")
    server = SchedulerServer(
        SqliteBackend(db), namespace="t", synchronous_planning=True
    )
    server.state._chaos = _CrashAtWrite(2)
    with pytest.raises(ChaosInjected):
        _submit_sales_job(server)
    del server  # crash before any retry

    server2 = SchedulerServer(SqliteBackend(db), namespace="t")
    assert server2.recovery_stats.get("torn_job_discarded") == 1
    jobs = list(server2.state.kv.get_prefix("/ballista/t/jobs"))
    assert len(jobs) == 1
    js = pb.JobStatus()
    js.ParseFromString(jobs[0][1])
    assert js.WhichOneof("status") == "failed"
    assert "resubmit" in js.failed.error
    # settings of the torn job were swept too
    assert list(server2.state.kv.get_prefix("/ballista/t/settings")) == []


def test_sqlite_put_all_is_atomic(tmp_path):
    kv = SqliteBackend(str(tmp_path / "kv.db"))
    kv.put("keep", b"old")
    with pytest.raises(Exception):
        # the third item is unbindable: the whole batch must roll back
        kv.put_all([("keep", b"new"), ("a", b"1"), ("bad", object())])
    assert kv.get("keep") == b"old"
    assert kv.get("a") is None
    kv.put_all([("a", b"1"), ("b", b"2")])
    assert kv.get("a") == b"1" and kv.get("b") == b"2"


def test_memory_put_all_and_delete():
    kv = MemoryBackend()
    kv.put_all([("a", b"1"), ("ab", b"2")])
    assert kv.get("a") == b"1" and kv.get("ab") == b"2"
    # exact-key delete must not eat sibling keys sharing the prefix
    kv.delete("a")
    assert kv.get("a") is None and kv.get("ab") == b"2"


def test_sqlite_delete_is_exact_key(tmp_path):
    kv = SqliteBackend(str(tmp_path / "kv.db"))
    kv.put("/a/1/2", b"x")
    kv.put("/a/1/20", b"y")
    kv.delete("/a/1/2")
    assert kv.get("/a/1/2") is None
    assert kv.get("/a/1/20") == b"y"


# -- seeded crash + restart acceptance run ----------------------------------

GROUP_BY_SQL = (
    "select region, sum(amount) as s, count(*) as n from sales "
    "group by region order by region"
)
JOIN_SQL = (
    "select region, sum(amount * bonus) as weighted from sales, regions "
    "where region = name group by region order by region"
)

CLIENT_SETTINGS = {
    "ballista.shuffle.partitions": "4",
    # generous transient-retry budget so clients and executors ride the
    # crash->restart UNAVAILABLE gap instead of surfacing it
    "ballista.rpc.retries": "20",
    "ballista.rpc.backoff_ms": "50",
}


CRASH_RATE = 0.05


def _find_crash_seed():
    """Deterministically scan for a seed where generation 0 crashes the
    scheduler at accepted status 2-4 (mid-job: after planning, during
    execution of the first query's 8 tasks) and generation 1 survives the
    whole run's status horizon (~16 statuses for both queries plus
    redelivered duplicates; 120 is comfortably past it) — pure hashing, no
    cluster involved, so the scan result is stable forever."""
    for seed in range(20000):
        inj = ChaosInjector(seed, rate=CRASH_RATE, sites={"scheduler.crash"})

        def fires_at(gen, horizon):
            for n in range(1, horizon):
                if inj.should_inject("scheduler.crash", f"g{gen}/status{n}"):
                    return n
            return None

        first = fires_at(0, 40)
        if first in (2, 3, 4) and fires_at(1, 120) is None:
            return seed
    pytest.fail("no crash seed found in scan range")


def _register(ctx, sales_table):
    ctx.register_record_batches("sales", sales_table, n_partitions=4)
    ctx.register_record_batches(
        "regions",
        pa.table({"name": ["east", "west", "north"], "bonus": [1.0, 2.0, 3.0]}),
    )


def _run_queries(cluster, sales_table, settings):
    from ballista_tpu.client import BallistaContext

    ctx = BallistaContext(*cluster.scheduler_addr, settings=settings)
    _register(ctx, sales_table)
    out = {}
    for name, sql in (("group_by", GROUP_BY_SQL), ("join", JOIN_SQL)):
        out[name] = ctx.sql(sql).collect()
    ctx.close()
    return out


def test_scheduler_crash_and_restart_is_bit_identical(tmp_path, sales_table):
    """ISSUE 6 acceptance: a seeded chaos run crashes the scheduler mid-job
    (after planning: the crash site keys on accepted task statuses);
    a FRESH SchedulerServer restarted on the same SqliteBackend store
    resumes the job from the durable state + assignment ledger and the
    results are bit-identical to the fault-free run. No task an executor
    still owned is re-executed (task_retry == orphan_reassigned == 0)."""
    from ballista_tpu.executor.runtime import StandaloneCluster
    from ballista_tpu.ops.runtime import recovery_stats

    crash_seed = _find_crash_seed()

    clean_cluster = StandaloneCluster(n_executors=2)
    try:
        clean = _run_queries(clean_cluster, sales_table, CLIENT_SETTINGS)
    finally:
        clean_cluster.shutdown()

    cluster_config = BallistaConfig({
        "ballista.chaos.rate": str(CRASH_RATE),
        "ballista.chaos.seed": str(crash_seed),
        "ballista.chaos.sites": "scheduler.crash",
        "ballista.rpc.retries": "20",
        "ballista.rpc.backoff_ms": "50",
    })
    recovery_stats(reset=True)
    cluster = StandaloneCluster(
        n_executors=2,
        kv=SqliteBackend(str(tmp_path / "sched.db")),
        config=cluster_config,
    )
    # watchdog: restart the scheduler on the same store as soon as the
    # chaos crash fires (an external supervisor's job in a real deployment)
    stop = threading.Event()

    def supervisor():
        while not stop.is_set():
            if cluster.scheduler_impl.crashed:
                cluster.restart_scheduler()
            time.sleep(0.02)

    sup = threading.Thread(target=supervisor, daemon=True)
    sup.start()
    try:
        chaotic = _run_queries(cluster, sales_table, CLIENT_SETTINGS)
    finally:
        stop.set()
        sup.join(timeout=5)
        cluster.shutdown()

    for name in ("group_by", "join"):
        assert chaotic[name].equals(clean[name]), (
            name, chaotic[name].to_pydict(), clean[name].to_pydict(),
        )
    stats = recovery_stats(reset=True)
    assert stats.get("chaos_scheduler_crash", 0) >= 1, stats
    assert stats.get("scheduler_restart", 0) >= 1, stats
    assert stats.get("restart_job_resumed", 0) >= 1, stats
    # restart reconciliation must NOT have re-executed owned work
    assert stats.get("task_retry", 0) == 0, stats
    assert stats.get("orphan_reassigned", 0) == 0, stats


def test_plan_write_chaos_retries_to_bit_identical(sales_table):
    """scheduler.plan_write armed at a nonzero rate: torn planning attempts
    abort atomically and retry with rotated keys; results stay
    bit-identical to fault-free and the plan_retry counter shows the tears
    actually happened."""
    from ballista_tpu.executor.runtime import StandaloneCluster
    from ballista_tpu.ops.runtime import recovery_stats

    clean_cluster = StandaloneCluster(n_executors=2)
    try:
        clean = _run_queries(clean_cluster, sales_table, CLIENT_SETTINGS)
    finally:
        clean_cluster.shutdown()

    # seed scanned over the PLAN-coordINATE key space the two queries can
    # produce: attempt 0 tears on at least one staged write, attempts 1-3
    # are clean for EVERY candidate key — so planning deterministically
    # converges on the first retry, inside the default budget
    rate = 0.02
    candidates = (
        [f"stage{s}" for s in range(1, 5)]
        + [f"{s}/{p}" for s in range(1, 5) for p in range(4)]
        + ["commit"]
    )

    def _tears(inj, key, attempt):
        return inj.should_inject("scheduler.plan_write", f"{key}@a{attempt}")

    # the tear must land on a key every submission provably produces
    # (stage 1 and its partition 0 exist in any multi-stage job; commit
    # always runs) — a seed tearing only on a key this plan never stages
    # would make plan_retry 0
    always_present = ("stage1", "1/0", "commit")
    seed = next(
        s for s in range(5000)
        if (inj := ChaosInjector(s, rate, sites={"scheduler.plan_write"}))
        and any(_tears(inj, k, 0) for k in always_present)
        and not any(
            _tears(inj, k, a) for k in candidates for a in (1, 2, 3)
        )
    )
    cluster_config = BallistaConfig({
        "ballista.chaos.rate": str(rate),
        "ballista.chaos.seed": str(seed),
        "ballista.chaos.sites": "scheduler.plan_write",
    })
    recovery_stats(reset=True)
    cluster = StandaloneCluster(n_executors=2, config=cluster_config)
    try:
        chaotic = _run_queries(cluster, sales_table, CLIENT_SETTINGS)
    finally:
        cluster.shutdown()
    for name in ("group_by", "join"):
        assert chaotic[name].equals(clean[name]), name
    stats = recovery_stats(reset=True)
    assert stats.get("plan_retry", 0) >= 1, stats

"""SPMD stage execution through the REAL distributed planner: a
Partial -> hash exchange -> Final aggregation collapses into one
SpmdAggregateExec stage whose exchange is a psum over the 8-device mesh
(config ballista.tpu.spmd_stages)."""

import numpy as np
import pyarrow as pa
import pytest

from ballista_tpu.client import BallistaContext
from ballista_tpu.config import BallistaConfig
from ballista_tpu.distributed.planner import DistributedPlanner
from ballista_tpu.engine import ExecutionContext
from ballista_tpu.executor.runtime import StandaloneCluster
from ballista_tpu.logical import col, functions as F
from ballista_tpu.parallel.spmd_stage import SpmdAggregateExec

SPMD_SETTINGS = {
    "ballista.executor.backend": "tpu",
    "ballista.tpu.spmd_stages": "true",
    "ballista.tpu.mesh": "data:8",
}


def _sales(n=4000, seed=3):
    rng = np.random.default_rng(seed)
    return pa.table(
        {
            "region": pa.array(
                np.array(["east", "west", "north", "south"])[
                    rng.integers(0, 4, n)
                ]
            ),
            "amount": pa.array(rng.uniform(0, 100, n)),
            "qty": pa.array(rng.integers(1, 50, n), type=pa.int64()),
        }
    )


def _physical(table, settings):
    ctx = ExecutionContext(BallistaConfig(settings))
    ctx.register_record_batches("sales", table, n_partitions=4)
    df = ctx.table("sales").aggregate(
        [col("region")],
        [F.sum(col("amount")).alias("s"), F.count(col("qty")).alias("c"),
         F.min(col("amount")).alias("mn"), F.sum(col("qty")).alias("sq")],
    )
    return ctx, ctx.create_physical_plan(df.logical_plan())


def test_planner_fuses_partial_final_into_one_stage():
    table = _sales()
    _, phys = _physical(table, SPMD_SETTINGS)
    cfg = BallistaConfig(SPMD_SETTINGS)

    fused = DistributedPlanner(cfg).plan_query_stages("job", phys)
    plain = DistributedPlanner().plan_query_stages("job", phys)

    def nodes(plan):
        yield plan
        for c in plan.children():
            yield from nodes(c)

    fused_types = [type(n).__name__ for s in fused for n in nodes(s)]
    assert "SpmdAggregateExec" in fused_types
    # the exchange stage disappeared: one stage instead of two
    assert len(fused) == len(plain) - 1


def test_spmd_exec_serde_roundtrip():
    from ballista_tpu.serde.physical import phys_plan_from_proto, phys_plan_to_proto

    table = _sales()
    cfg = BallistaConfig(SPMD_SETTINGS)
    _, phys = _physical(table, SPMD_SETTINGS)
    stages = DistributedPlanner(cfg).plan_query_stages("job", phys)
    spmd = None
    for s in stages:
        def find(n):
            if isinstance(n, SpmdAggregateExec):
                return n
            for c in n.children():
                r = find(c)
                if r is not None:
                    return r
            return None
        spmd = spmd or find(s)
    assert spmd is not None
    back = phys_plan_from_proto(phys_plan_to_proto(spmd))
    assert isinstance(back, SpmdAggregateExec)
    assert back.schema() == spmd.schema()


def test_mesh_program_matches_host():
    """The mesh program's result equals the plain host aggregation."""
    from ballista_tpu.physical.plan import TaskContext

    table = _sales()
    cfg = BallistaConfig(SPMD_SETTINGS)
    ctx, phys = _physical(table, SPMD_SETTINGS)
    stages = DistributedPlanner(cfg).plan_query_stages("job", phys)

    def find(n):
        if isinstance(n, SpmdAggregateExec):
            return n
        for c in n.children():
            r = find(c)
            if r is not None:
                return r
        return None

    spmd = next(s for s in (find(st) for st in stages) if s is not None)
    tctx = TaskContext(config=cfg, work_dir="/tmp", job_id="t")
    out = pa.Table.from_batches(list(spmd.execute(0, tctx))).sort_by("region")
    # the host fallback would produce identical rows; require the mesh path
    assert spmd.last_path == "mesh"

    host = (
        table.group_by("region")
        .aggregate([("amount", "sum"), ("qty", "count"), ("amount", "min"),
                    ("qty", "sum")])
        .sort_by("region")
    )
    assert out.column("region").to_pylist() == host.column("region").to_pylist()
    assert out.column("c").to_pylist() == host.column("qty_count").to_pylist()
    assert out.column("sq").to_pylist() == host.column("qty_sum").to_pylist()
    np.testing.assert_allclose(
        out.column("s").to_numpy(), host.column("amount_sum").to_numpy(),
        rtol=1e-4,
    )
    np.testing.assert_allclose(
        out.column("mn").to_numpy(), host.column("amount_min").to_numpy(),
        rtol=1e-6, atol=1e-6,
    )


def _find_spmd(stages):
    def find(n):
        if isinstance(n, SpmdAggregateExec):
            return n
        for c in n.children():
            r = find(c)
            if r is not None:
                return r
        return None

    return next(s for s in (find(st) for st in stages) if s is not None)


def _run_spmd(table, group_cols, aggs, n_partitions=4, settings=SPMD_SETTINGS):
    from ballista_tpu.physical.plan import TaskContext

    cfg = BallistaConfig(settings)
    ctx = ExecutionContext(cfg)
    ctx.register_record_batches("t", table, n_partitions=n_partitions)
    df = ctx.table("t").aggregate([col(c) for c in group_cols], aggs)
    phys = ctx.create_physical_plan(df.logical_plan())
    spmd = _find_spmd(DistributedPlanner(cfg).plan_query_stages("job", phys))
    tctx = TaskContext(config=cfg, work_dir="/tmp", job_id="t")
    out = pa.Table.from_batches(list(spmd.execute(0, tctx)))
    return spmd, out


def test_mesh_high_cardinality_takes_mesh_path():
    """>=100k groups run the sorted chunked-segment mesh path (per-shard
    reads + in-program segment fold + psum), matching the host oracle —
    the unrolled path's 1024-group ceiling does not apply to the mesh."""
    rng = np.random.default_rng(7)
    N, G = 300_000, 130_000
    table = pa.table(
        {
            "k": pa.array(rng.integers(0, G, N).astype(np.int64)),
            "v": pa.array(rng.uniform(0, 100, N)),
            "q": pa.array(rng.integers(1, 50, N).astype(np.int64)),
        }
    )
    spmd, out = _run_spmd(
        table, ["k"],
        [F.sum(col("v")).alias("s"), F.count(col("q")).alias("c"),
         F.min(col("v")).alias("mn"), F.sum(col("q")).alias("sq")],
        n_partitions=5,  # 5 partitions over 8 shards: empty shards included
    )
    assert spmd.last_path == "mesh"
    ora = (
        table.group_by("k")
        .aggregate([("v", "sum"), ("q", "count"), ("v", "min"), ("q", "sum")])
        .sort_by("k")
    )
    got = out.sort_by("k")
    assert got.num_rows == ora.num_rows > 100_000
    np.testing.assert_array_equal(
        got.column("k").to_numpy(), ora.column("k").to_numpy()
    )
    np.testing.assert_array_equal(
        got.column("c").to_numpy(), ora.column("q_count").to_numpy()
    )
    np.testing.assert_array_equal(
        got.column("sq").to_numpy(), ora.column("q_sum").to_numpy()
    )
    np.testing.assert_allclose(
        got.column("s").to_numpy(), ora.column("v_sum").to_numpy(), rtol=1e-4
    )
    np.testing.assert_allclose(
        got.column("mn").to_numpy(), ora.column("v_min").to_numpy(), rtol=1e-6
    )


def test_mesh_multi_column_key():
    """Composite group keys get globally-consistent codes from the
    per-shard-distincts union ranking."""
    rng = np.random.default_rng(11)
    n = 6000
    table = pa.table(
        {
            "region": pa.array(
                np.array(["east", "west", "north", "south"])[rng.integers(0, 4, n)]
            ),
            "tier": pa.array(rng.integers(0, 7, n).astype(np.int64)),
            "amount": pa.array(rng.uniform(0, 100, n)),
        }
    )
    spmd, out = _run_spmd(
        table, ["region", "tier"],
        [F.sum(col("amount")).alias("s"), F.count(col("amount")).alias("c")],
        n_partitions=6,
    )
    assert spmd.last_path == "mesh"
    ora = (
        table.group_by(["region", "tier"])
        .aggregate([("amount", "sum"), ("amount", "count")])
        .sort_by([("region", "ascending"), ("tier", "ascending")])
    )
    got = out.sort_by([("region", "ascending"), ("tier", "ascending")])
    assert got.column("region").to_pylist() == ora.column("region").to_pylist()
    assert got.column("tier").to_pylist() == ora.column("tier").to_pylist()
    assert got.column("c").to_pylist() == ora.column("amount_count").to_pylist()
    np.testing.assert_allclose(
        got.column("s").to_numpy(), ora.column("amount_sum").to_numpy(),
        rtol=1e-4,
    )


def test_mesh_skewed_run_lengths_unify_tile_width():
    """One shard holds a single hot group (long runs -> large L1) while the
    rest are high-cardinality (L1=8): shards must rebuild their layouts to
    one shared tile width before stacking (the force_L1 branch)."""
    rng = np.random.default_rng(13)
    # first half: ONE mega-group (its shard sees a 0 percentile over the
    # group grid -> L1=8); second half: every group 1..1100 at count 16
    # (-> L1=16). The shards must agree on a tile width, so at least one
    # rebuilds with force_L1.
    G = 1100  # > 1024: the sorted mesh path
    mega = np.zeros(G * 32, dtype=np.int64)
    dense = np.tile(np.arange(1, G + 1, dtype=np.int64), 32)
    keys = np.concatenate([mega, dense])
    table = pa.table(
        {
            "k": pa.array(keys),
            "v": pa.array(rng.uniform(0, 10, len(keys))),
        }
    )
    spmd, out = _run_spmd(
        table, ["k"],
        [F.sum(col("v")).alias("s"), F.count(col("v")).alias("c")],
        n_partitions=2,
    )
    assert spmd.last_path == "mesh"
    ora = (
        table.group_by("k").aggregate([("v", "sum"), ("v", "count")]).sort_by("k")
    )
    got = out.sort_by("k")
    assert got.num_rows == ora.num_rows > 1024  # sorted mesh path
    np.testing.assert_array_equal(
        got.column("c").to_numpy(), ora.column("v_count").to_numpy()
    )
    np.testing.assert_allclose(
        got.column("s").to_numpy(), ora.column("v_sum").to_numpy(), rtol=1e-4
    )


def test_mesh_fewer_partitions_than_devices():
    """Empty shards contribute the identity; results stay exact."""
    table = _sales(n=500, seed=5)
    spmd, out = _run_spmd(
        table, ["region"],
        [F.sum(col("qty")).alias("sq"), F.max(col("amount")).alias("mx")],
        n_partitions=2,  # 6 of 8 shards empty
    )
    assert spmd.last_path == "mesh"
    ora = (
        table.group_by("region")
        .aggregate([("qty", "sum"), ("amount", "max")])
        .sort_by("region")
    )
    got = out.sort_by("region")
    assert got.column("sq").to_pylist() == ora.column("qty_sum").to_pylist()
    np.testing.assert_allclose(
        got.column("mx").to_numpy(), ora.column("amount_max").to_numpy(),
        rtol=1e-6,
    )


def test_mesh_readback_recorded():
    """Multi-chip readback accounting (ISSUE 3): the mesh aggregate's d2h
    result transfer must flow through record_readback on BOTH programs —
    unrolled (G <= 1024) and sorted (G > 1024) — so bench.py's per-config
    readback fields stop undercounting pod runs."""
    from ballista_tpu.ops.runtime import readback_stats

    # unrolled mesh program
    readback_stats(reset=True)
    table = _sales(n=3000, seed=21)
    spmd, out = _run_spmd(
        table, ["region"],
        [F.sum(col("amount")).alias("s"), F.count(col("qty")).alias("c")],
    )
    assert spmd.last_path == "mesh"
    s = readback_stats(reset=True)
    assert s["readbacks"] >= 1
    assert s["rows"] > 0 and s["bytes"] > 0

    # sorted mesh program (G > MAX_GROUPS)
    rng = np.random.default_rng(23)
    n, G = 60_000, 5_000
    big = pa.table(
        {
            "k": pa.array(rng.integers(0, G, n).astype(np.int64)),
            "v": pa.array(rng.uniform(0, 10, n)),
        }
    )
    spmd, out = _run_spmd(
        big, ["k"], [F.sum(col("v")).alias("s"), F.count(col("v")).alias("c")]
    )
    assert spmd.last_path == "mesh"
    assert out.num_rows > 1024  # the sorted path actually ran
    s = readback_stats(reset=True)
    assert s["readbacks"] >= 1
    assert s["rows"] >= out.num_rows  # padded group axis covers every group
    assert s["bytes"] > 0


def test_mesh_join_readback_recorded():
    """The SPMD mesh join reads its matching plane back over d2h — those
    transfers must be accounted too (they were the unrecorded sites ISSUE 3
    calls out in parallel/spmd_join.py)."""
    import pyarrow.parquet as pq  # noqa: F401  (parity with other suites)

    from ballista_tpu.ops.runtime import readback_stats
    from ballista_tpu.parallel.spmd_join import SpmdJoinExec
    from ballista_tpu.physical.plan import TaskContext

    rng = np.random.default_rng(29)
    n_b, n_p = 500, 4000
    build = pa.table(
        {
            "bk": pa.array(np.arange(n_b).astype(np.int64)),
            "bv": pa.array(rng.uniform(0, 1, n_b)),
        }
    )
    probe = pa.table(
        {
            "pk": pa.array(rng.integers(0, n_b + 50, n_p).astype(np.int64)),
            "pv": pa.array(rng.uniform(0, 1, n_p)),
        }
    )
    cfg = BallistaConfig(SPMD_SETTINGS)
    ctx = ExecutionContext(cfg)
    ctx.register_record_batches("b", build, n_partitions=2)
    ctx.register_record_batches("p", probe, n_partitions=3)
    df = ctx.table("b").join(ctx.table("p"), ["bk"], ["pk"], how="inner")
    phys = ctx.create_physical_plan(df.logical_plan())
    stages = DistributedPlanner(cfg).plan_query_stages("job", phys)

    def find(n):
        if isinstance(n, SpmdJoinExec):
            return n
        for c in n.children():
            r = find(c)
            if r is not None:
                return r
        return None

    spmd = next((find(st) for st in stages if find(st) is not None), None)
    assert spmd is not None, "planner did not emit SpmdJoinExec"
    readback_stats(reset=True)
    tctx = TaskContext(config=cfg, work_dir="/tmp", job_id="j")
    out = pa.Table.from_batches(list(spmd.execute(0, tctx)))
    assert spmd.last_path == "mesh"
    s = readback_stats(reset=True)
    assert s["readbacks"] >= 2  # matched row ids + probe row ids at minimum
    assert s["rows"] > 0 and s["bytes"] > 0
    # sanity: the join itself is right
    ora = build.join(probe, keys="bk", right_keys="pk", join_type="inner")
    assert out.num_rows == ora.num_rows


def test_mesh_failure_falls_back_and_is_surfaced(monkeypatch, caplog):
    """A broken mesh path must not be invisible: the host fallback still
    returns correct rows, the tracing counter increments, and a warning
    with the stage fingerprint is logged once."""
    import logging

    from ballista_tpu.physical.plan import TaskContext
    from ballista_tpu.utils import tracing

    table = _sales(n=800, seed=9)
    cfg = BallistaConfig(SPMD_SETTINGS)
    ctx = ExecutionContext(cfg)
    ctx.register_record_batches("t", table, n_partitions=3)
    df = ctx.table("t").aggregate(
        [col("region")], [F.sum(col("amount")).alias("s")]
    )
    phys = ctx.create_physical_plan(df.logical_plan())
    spmd = _find_spmd(DistributedPlanner(cfg).plan_query_stages("job", phys))

    def boom(ctx):
        raise RuntimeError("injected mesh failure")

    monkeypatch.setattr(spmd, "_execute_mesh", boom)
    SpmdAggregateExec._warned_fingerprints.clear()
    tracing.reset()
    tctx = TaskContext(config=cfg, work_dir="/tmp", job_id="t")
    with caplog.at_level(logging.WARNING, logger="ballista.spmd"):
        out = pa.Table.from_batches(list(spmd.execute(0, tctx)))
    assert spmd.last_path == "host"
    c = tracing.counters()
    assert c.get("spmd.host_fallback") == 1
    assert c.get("spmd.host_fallback_error") == 1
    assert c.get("spmd.mesh") is None
    assert any("injected mesh failure" in r.message and spmd.fingerprint()
               in r.message for r in caplog.records)
    ora = table.group_by("region").aggregate([("amount", "sum")]).sort_by("region")
    got = out.sort_by("region")
    np.testing.assert_allclose(
        got.column("s").to_numpy(), ora.column("amount_sum").to_numpy(),
        rtol=1e-4,
    )
    # a second failure on the same stage does not re-warn
    caplog.clear()
    with caplog.at_level(logging.WARNING, logger="ballista.spmd"):
        list(spmd.execute(0, tctx))
    assert not caplog.records
    assert tracing.counters().get("spmd.host_fallback") == 2


def test_distributed_spmd_end_to_end(sales_table):
    """Full path: BallistaContext -> scheduler -> DistributedPlanner(spmd) ->
    executor runs the mesh program -> client fetches the result."""
    cluster = StandaloneCluster(
        n_executors=1, config=BallistaConfig(SPMD_SETTINGS)
    )
    try:
        host, port = cluster.scheduler_addr
        c = BallistaContext(host, port, settings=SPMD_SETTINGS)
        c.register_record_batches("sales", sales_table, n_partitions=3)
        out = (
            c.table("sales")
            .aggregate([col("region")], [F.sum(col("amount")).alias("total"),
                                         F.count(col("id")).alias("n")])
            .sort(col("region").sort())
            .collect()
        )
        assert out.column("region").to_pylist() == ["east", "north", "west"]
        assert out.column("total").to_pylist() == [120.0, 40.0, 145.0]
        assert out.column("n").to_pylist() == [4, 2, 4]
        c.close()
    finally:
        cluster.shutdown()


def test_admission_declines_mesh_when_model_prefers_host(tmp_path):
    """Mesh admission rides the cost model (ISSUE 16 satellite): with BOTH
    the mesh and host rates warm for this stage shape and the mesh
    predicted slower, execute() routes to the host subplan up front (no
    mesh launch) — last_path == "host", identical rows. Re-seeding the
    model mesh-cheap flips the same node back to the mesh."""
    from ballista_tpu.ops import costmodel
    from ballista_tpu.physical.plan import TaskContext
    from ballista_tpu.utils import tracing

    table = _sales()
    settings = {
        **SPMD_SETTINGS,
        "ballista.tpu.cost_model": "true",
        "ballista.tpu.cost_model_dir": str(tmp_path / "costs"),
    }
    cfg = BallistaConfig(settings)
    ctx, phys = _physical(table, settings)
    stages = DistributedPlanner(cfg).plan_query_stages("job", phys)

    def find(n):
        if isinstance(n, SpmdAggregateExec):
            return n
        for c in n.children():
            r = find(c)
            if r is not None:
                return r
        return None

    spmd = next(s for s in (find(st) for st in stages) if s is not None)
    fp = spmd.fingerprint()[:12]
    costmodel.reset(clear_dir=True)
    costmodel.configure(cfg)
    try:
        costmodel.seed("mesh.agg|" + fp, 1.0, 10.0)
        costmodel.seed("mesh.agg.host|" + fp, 1.0, 1e-4, engine="host")
        declined_before = tracing.counters().get("spmd.host_declined", 0)
        tctx = TaskContext(config=cfg, work_dir="/tmp", job_id="t")
        host_out = pa.Table.from_batches(
            list(spmd.execute(0, tctx))
        ).sort_by("region")
        assert spmd.last_path == "host"
        assert (
            tracing.counters().get("spmd.host_declined", 0)
            == declined_before + 1
        )

        # inverse seeding (seed replaces the bucket history) re-admits the
        # mesh on the very next execute — and the rows cannot move
        costmodel.seed("mesh.agg|" + fp, 1.0, 1e-6)
        costmodel.seed("mesh.agg.host|" + fp, 1.0, 10.0, engine="host")
        mesh_out = pa.Table.from_batches(
            list(spmd.execute(0, tctx))
        ).sort_by("region")
        assert spmd.last_path == "mesh"
        # summation ORDER differs between paths: exact on every column but
        # the float sum, which gets the same tolerance the mesh-vs-host
        # equivalence test uses
        for name in ("region", "c", "sq"):
            assert (mesh_out.column(name).to_pylist()
                    == host_out.column(name).to_pylist())
        np.testing.assert_allclose(
            mesh_out.column("s").to_numpy(), host_out.column("s").to_numpy(),
            rtol=1e-4,
        )
        np.testing.assert_allclose(
            mesh_out.column("mn").to_numpy(), host_out.column("mn").to_numpy(),
            rtol=1e-6, atol=1e-6,
        )
    finally:
        costmodel.reset(clear_dir=True)


def test_admission_stays_mesh_while_host_rate_is_cold(tmp_path):
    """A warm mesh rate alone must NOT decline: the gate needs both sides
    warm, so the cold-start behavior is exactly the pre-model ladder."""
    from ballista_tpu.ops import costmodel
    from ballista_tpu.physical.plan import TaskContext

    table = _sales()
    settings = {
        **SPMD_SETTINGS,
        "ballista.tpu.cost_model": "true",
        "ballista.tpu.cost_model_dir": str(tmp_path / "costs"),
    }
    cfg = BallistaConfig(settings)
    ctx, phys = _physical(table, settings)
    stages = DistributedPlanner(cfg).plan_query_stages("job", phys)

    def find(n):
        if isinstance(n, SpmdAggregateExec):
            return n
        for c in n.children():
            r = find(c)
            if r is not None:
                return r
        return None

    spmd = next(s for s in (find(st) for st in stages) if s is not None)
    costmodel.reset(clear_dir=True)
    costmodel.configure(cfg)
    try:
        # arbitrarily slow mesh, but no host observation → admit
        costmodel.seed("mesh.agg|" + spmd.fingerprint()[:12], 1.0, 1e9)
        tctx = TaskContext(config=cfg, work_dir="/tmp", job_id="t")
        list(spmd.execute(0, tctx))
        assert spmd.last_path == "mesh"
    finally:
        costmodel.reset(clear_dir=True)

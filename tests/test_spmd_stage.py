"""SPMD stage execution through the REAL distributed planner: a
Partial -> hash exchange -> Final aggregation collapses into one
SpmdAggregateExec stage whose exchange is a psum over the 8-device mesh
(config ballista.tpu.spmd_stages)."""

import numpy as np
import pyarrow as pa
import pytest

from ballista_tpu.client import BallistaContext
from ballista_tpu.config import BallistaConfig
from ballista_tpu.distributed.planner import DistributedPlanner
from ballista_tpu.engine import ExecutionContext
from ballista_tpu.executor.runtime import StandaloneCluster
from ballista_tpu.logical import col, functions as F
from ballista_tpu.parallel.spmd_stage import SpmdAggregateExec

SPMD_SETTINGS = {
    "ballista.executor.backend": "tpu",
    "ballista.tpu.spmd_stages": "true",
    "ballista.tpu.mesh": "data:8",
}


def _sales(n=4000, seed=3):
    rng = np.random.default_rng(seed)
    return pa.table(
        {
            "region": pa.array(
                np.array(["east", "west", "north", "south"])[
                    rng.integers(0, 4, n)
                ]
            ),
            "amount": pa.array(rng.uniform(0, 100, n)),
            "qty": pa.array(rng.integers(1, 50, n), type=pa.int64()),
        }
    )


def _physical(table, settings):
    ctx = ExecutionContext(BallistaConfig(settings))
    ctx.register_record_batches("sales", table, n_partitions=4)
    df = ctx.table("sales").aggregate(
        [col("region")],
        [F.sum(col("amount")).alias("s"), F.count(col("qty")).alias("c"),
         F.min(col("amount")).alias("mn"), F.sum(col("qty")).alias("sq")],
    )
    return ctx, ctx.create_physical_plan(df.logical_plan())


def test_planner_fuses_partial_final_into_one_stage():
    table = _sales()
    _, phys = _physical(table, SPMD_SETTINGS)
    cfg = BallistaConfig(SPMD_SETTINGS)

    fused = DistributedPlanner(cfg).plan_query_stages("job", phys)
    plain = DistributedPlanner().plan_query_stages("job", phys)

    def nodes(plan):
        yield plan
        for c in plan.children():
            yield from nodes(c)

    fused_types = [type(n).__name__ for s in fused for n in nodes(s)]
    assert "SpmdAggregateExec" in fused_types
    # the exchange stage disappeared: one stage instead of two
    assert len(fused) == len(plain) - 1


def test_spmd_exec_serde_roundtrip():
    from ballista_tpu.serde.physical import phys_plan_from_proto, phys_plan_to_proto

    table = _sales()
    cfg = BallistaConfig(SPMD_SETTINGS)
    _, phys = _physical(table, SPMD_SETTINGS)
    stages = DistributedPlanner(cfg).plan_query_stages("job", phys)
    spmd = None
    for s in stages:
        def find(n):
            if isinstance(n, SpmdAggregateExec):
                return n
            for c in n.children():
                r = find(c)
                if r is not None:
                    return r
            return None
        spmd = spmd or find(s)
    assert spmd is not None
    back = phys_plan_from_proto(phys_plan_to_proto(spmd))
    assert isinstance(back, SpmdAggregateExec)
    assert back.schema() == spmd.schema()


def test_mesh_program_matches_host():
    """The mesh program's result equals the plain host aggregation."""
    from ballista_tpu.physical.plan import TaskContext

    table = _sales()
    cfg = BallistaConfig(SPMD_SETTINGS)
    ctx, phys = _physical(table, SPMD_SETTINGS)
    stages = DistributedPlanner(cfg).plan_query_stages("job", phys)

    def find(n):
        if isinstance(n, SpmdAggregateExec):
            return n
        for c in n.children():
            r = find(c)
            if r is not None:
                return r
        return None

    spmd = next(s for s in (find(st) for st in stages) if s is not None)
    tctx = TaskContext(config=cfg, work_dir="/tmp", job_id="t")
    out = pa.Table.from_batches(list(spmd.execute(0, tctx))).sort_by("region")
    # the host fallback would produce identical rows; require the mesh path
    assert spmd.last_path == "mesh"

    host = (
        table.group_by("region")
        .aggregate([("amount", "sum"), ("qty", "count"), ("amount", "min"),
                    ("qty", "sum")])
        .sort_by("region")
    )
    assert out.column("region").to_pylist() == host.column("region").to_pylist()
    assert out.column("c").to_pylist() == host.column("qty_count").to_pylist()
    assert out.column("sq").to_pylist() == host.column("qty_sum").to_pylist()
    np.testing.assert_allclose(
        out.column("s").to_numpy(), host.column("amount_sum").to_numpy(),
        rtol=1e-4,
    )
    np.testing.assert_allclose(
        out.column("mn").to_numpy(), host.column("amount_min").to_numpy(),
        rtol=1e-6, atol=1e-6,
    )


def test_distributed_spmd_end_to_end(sales_table):
    """Full path: BallistaContext -> scheduler -> DistributedPlanner(spmd) ->
    executor runs the mesh program -> client fetches the result."""
    cluster = StandaloneCluster(
        n_executors=1, config=BallistaConfig(SPMD_SETTINGS)
    )
    try:
        host, port = cluster.scheduler_addr
        c = BallistaContext(host, port, settings=SPMD_SETTINGS)
        c.register_record_batches("sales", sales_table, n_partitions=3)
        out = (
            c.table("sales")
            .aggregate([col("region")], [F.sum(col("amount")).alias("total"),
                                         F.count(col("id")).alias("n")])
            .sort(col("region").sort())
            .collect()
        )
        assert out.column("region").to_pylist() == ["east", "north", "west"]
        assert out.column("total").to_pylist() == [120.0, 40.0, 145.0]
        assert out.column("n").to_pylist() == [4, 2, 4]
        c.close()
    finally:
        cluster.shutdown()

"""Pipelined host->device ingestion: the bounded producer/consumer split
(ops/runtime.py ordered_map/pipelined_map, ops/stage.py prefetch vs ordered
consume, distributed/stages.py parallel shuffle fetches). The contract under
test everywhere: identical results and ordering at ANY worker count — the
pipeline may only change wall-clock, never bytes."""

import pathlib
import re
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from ballista_tpu.config import BallistaConfig
from ballista_tpu.engine import ExecutionContext
from ballista_tpu.ops import kernels
from ballista_tpu.ops.runtime import ingest_stats, ordered_map, pipelined_map
from ballista_tpu.physical.plan import TaskContext

QUERIES = pathlib.Path(__file__).parent.parent / "benchmarks" / "tpch" / "queries"


def _reset_stage_caches():
    """Simulate a fresh process: drop the in-memory stage cache and its HBM
    reservations so the next query re-prepares from scratch."""
    from ballista_tpu.ops.runtime import release_stage_residency, reset_residency

    for stage in kernels._stage_cache.values():
        if stage not in (None, False):
            release_stage_residency(stage)
    kernels._stage_cache.clear()
    kernels._stage_cache_pins.clear()
    kernels._stage_latest.clear()
    reset_residency()


@pytest.fixture(autouse=True)
def _fresh():
    _reset_stage_caches()
    ingest_stats(reset=True)
    yield
    _reset_stage_caches()


# -- unit: the pipeline primitives ------------------------------------------


def test_ordered_map_preserves_order():
    out = list(ordered_map(lambda x: x * x, range(17), workers=4, depth=2))
    assert out == [x * x for x in range(17)]


def test_ordered_map_zero_workers_is_serial_in_thread():
    seen = []

    def fn(x):
        seen.append(threading.current_thread())
        return x

    assert list(ordered_map(fn, [1, 2, 3], workers=0)) == [1, 2, 3]
    assert all(t is threading.main_thread() for t in seen)


def test_ordered_map_error_surfaces_at_its_position():
    def fn(x):
        if x == 2:
            raise ValueError("boom")
        return x

    it = ordered_map(fn, range(6), workers=3, depth=3)
    assert next(it) == 0
    assert next(it) == 1
    with pytest.raises(ValueError, match="boom"):
        next(it)


def test_pipelined_map_order_survives_adversarial_timing():
    # later items finish FIRST (decreasing sleeps): output order must still
    # match input order exactly
    def fn(x):
        time.sleep(0.002 * (8 - x))
        return x * 10

    out = list(pipelined_map(iter(range(8)), fn, workers=4, depth=4))
    assert out == [x * 10 for x in range(8)]


def test_pipelined_map_src_and_fn_errors_propagate():
    def bad_src():
        yield 1
        raise OSError("disk gone")

    with pytest.raises(OSError, match="disk gone"):
        list(pipelined_map(bad_src(), lambda x: x, workers=2))

    def bad_fn(x):
        if x == 1:
            raise RuntimeError("fn died")
        return x

    it = pipelined_map(iter(range(4)), bad_fn, workers=2)
    assert next(it) == 0
    with pytest.raises(RuntimeError, match="fn died"):
        next(it)


def test_pipelined_map_bounds_in_flight():
    """The reader must never run more than `depth` pulls ahead of the
    consumer — that bound is the host-RSS cap."""
    pulled = []

    def src():
        for i in range(12):
            pulled.append(i)
            yield i

    consumed = 0
    max_ahead = 0
    for _ in pipelined_map(src(), lambda x: x, workers=2, depth=2):
        time.sleep(0.02)  # slow consumer: an unbounded reader would hit 12
        consumed += 1
        max_ahead = max(max_ahead, len(pulled) - consumed)
    assert consumed == 12
    assert max_ahead <= 3  # depth + the item inside fn/result hand-off


def test_pipeline_overlap_micro_benchmark():
    """Sleep-based stages overlap regardless of core count: pipelined
    wall-clock must clearly beat the serial sum of stage times."""
    n, src_s, fn_s, consume_s = 8, 0.02, 0.02, 0.02

    def src():
        for i in range(n):
            time.sleep(src_s)  # "parquet read"
            yield i

    def fn(x):
        time.sleep(fn_s)  # "group ranking"
        return x

    def run(workers):
        t0 = time.perf_counter()
        for _ in pipelined_map(src(), fn, workers=workers, depth=2):
            time.sleep(consume_s)  # "encode/upload"
        return time.perf_counter() - t0

    serial = run(0)
    piped = run(2)
    assert piped < serial * 0.8, (piped, serial)


# -- engine: bit-identical results, measured overlap ------------------------


@pytest.fixture(scope="module")
def tpch_dir(tmp_path_factory):
    from benchmarks.tpch.datagen import generate

    d = tmp_path_factory.mktemp("tpch_ingest")
    generate(str(d), sf=0.005, parts=2)  # parts=2: multi-file scans
    return str(d)


def _tpu_ctx(tpch_dir, workers, extra=None):
    from benchmarks.tpch.datagen import register_all

    ctx = ExecutionContext(
        BallistaConfig(
            {
                "ballista.executor.backend": "tpu",
                "ballista.tpu.ingest_workers": str(workers),
                "ballista.batch.size": "4096",
                **(extra or {}),
            }
        )
    )
    register_all(ctx, tpch_dir)
    return ctx


@pytest.mark.parametrize("qname", ["q1", "q3", "q6"])
def test_pipelined_ingest_bit_identical(tpch_dir, qname):
    """The oracle contract: tpu_ingest_workers=2 must produce byte-for-byte
    the results of the serial (=0) path, including row order and the exact
    f32 accumulation order."""
    sql = (QUERIES / f"{qname}.sql").read_text()
    # strip ORDER BY (and its trailing LIMIT): the full result set compares
    # deterministically without depending on the host sort operator
    sql = re.sub(r"order\s+by[\s\S]*$", "", sql, flags=re.I)
    outs = {}
    for workers in (0, 2):
        _reset_stage_caches()
        outs[workers] = _tpu_ctx(tpch_dir, workers).sql(sql).collect()
    assert outs[0].schema == outs[2].schema
    assert outs[0].to_pydict() == outs[2].to_pydict()


def test_ingest_stats_recorded(tpch_dir):
    ingest_stats(reset=True)
    _tpu_ctx(tpch_dir, 2).sql(
        "select l_returnflag, sum(l_quantity) as s from lineitem "
        "group by l_returnflag"
    ).collect()
    stats = ingest_stats()
    assert stats["prepares"] >= 1
    assert stats["wall_s"] > 0
    assert stats["upload_s"] > 0
    assert 0.0 <= stats["overlap_frac"] < 1.0


def test_prepare_overlap_fraction_positive(tmp_path, monkeypatch):
    """Acceptance micro-benchmark: on a multi-batch scan with a measurable
    prefetch stage, the pipelined prepare overlaps host work (fraction > 0)
    and beats the serial wall-clock; the serial path shows no overlap."""
    import ballista_tpu.ops.stage as stage_mod
    from ballista_tpu.ops.stage import FusedAggregateStage

    # deterministic, core-count-independent stage costs: fixed sleeps in
    # the prefetch stage (group ranking) and the consume stage (narrowing)
    orig_codes = FusedAggregateStage._group_codes
    orig_narrow = stage_mod.narrow_column

    def slow_codes(self, batch):
        time.sleep(0.010)
        return orig_codes(self, batch)

    def slow_narrow(npcol, prior=None):
        time.sleep(0.005)
        return orig_narrow(npcol, prior)

    monkeypatch.setattr(FusedAggregateStage, "_group_codes", slow_codes)
    monkeypatch.setattr(stage_mod, "narrow_column", slow_narrow)

    rng = np.random.default_rng(0)
    n = 80_000
    t = pa.table(
        {
            "g": pa.array(rng.integers(0, 8, n), type=pa.int64()),
            "v": pa.array(rng.uniform(0, 10, n)),
            "w": pa.array(rng.integers(0, 100, n), type=pa.int64()),
        }
    )
    path = str(tmp_path / "t.parquet")
    pq.write_table(t, path)
    sql = "select g, sum(v) as sv, sum(w) as sw, count(*) as c from t group by g"

    def run(workers):
        _reset_stage_caches()
        ingest_stats(reset=True)
        ctx = ExecutionContext(
            BallistaConfig(
                {
                    "ballista.executor.backend": "tpu",
                    "ballista.tpu.ingest_workers": str(workers),
                    "ballista.batch.size": "4096",  # ~20 batches
                }
            )
        )
        ctx.register_parquet("t", path)
        out = ctx.sql(sql).collect()
        return out.sort_by("g").to_pydict(), ingest_stats()

    serial_out, serial_stats = run(0)
    piped_out, piped_stats = run(2)
    assert piped_out == serial_out
    assert serial_stats["overlap_frac"] == 0.0
    assert piped_stats["overlap_frac"] > 0.05, piped_stats
    assert piped_stats["wall_s"] < serial_stats["wall_s"], (
        piped_stats, serial_stats,
    )


# -- distributed: parallel shuffle fetches ----------------------------------


def _write_piece(path: pathlib.Path, schema: pa.Schema, values) -> None:
    path.parent.mkdir(parents=True, exist_ok=True)
    with pa.ipc.new_file(str(path), schema) as w:
        for v in values:
            w.write_batch(
                pa.record_batch([pa.array(v, type=pa.int64())], schema=schema)
            )


def test_shuffle_reader_concurrent_fetch_matches_serial(tmp_path):
    from ballista_tpu.distributed.stages import ShuffleLocation, ShuffleReaderExec

    schema = pa.schema([pa.field("v", pa.int64())])
    locs = []
    for m in range(5):
        base = tmp_path / f"map{m}"
        # piece 1 of map task m: two distinguishable batches
        _write_piece(base / "1.arrow", schema, [[m * 100], [m * 100 + 1]])
        locs.append(ShuffleLocation(f"e{m}", "localhost", 50050, str(base)))
    reader = ShuffleReaderExec(locs, schema, num_partitions=2)

    def run(workers):
        cfg = BallistaConfig({"ballista.tpu.ingest_workers": str(workers)})
        # trusted in-process context: local-disk reads, no fetcher
        return [
            b.column(0).to_pylist()
            for b in reader.execute(1, TaskContext(config=cfg))
        ]

    expect = [[m * 100 + b] for m in range(5) for b in range(2)]
    assert run(0) == expect
    assert run(2) == expect


def test_shuffle_fetcher_concurrent_preserves_location_order():
    """Adversarial completion order: later locations answer FIRST, yet
    batches must come out in location order (the serial loop's order)."""
    from ballista_tpu.distributed.stages import ShuffleLocation, ShuffleReaderExec

    schema = pa.schema([pa.field("v", pa.int64())])
    locs = [
        ShuffleLocation(f"e{m}", "host", 1, f"/nonexistent/{m}")
        for m in range(4)
    ]
    fetched_order = []

    def fetcher(loc, piece_idx):
        m = int(loc.executor_id[1:])
        time.sleep(0.01 * (4 - m))
        fetched_order.append(m)
        yield pa.record_batch([pa.array([m], type=pa.int64())], schema=schema)

    cfg = BallistaConfig({"ballista.tpu.ingest_workers": "4"})
    ctx = TaskContext(config=cfg, shuffle_fetcher=fetcher)
    reader = ShuffleReaderExec(locs, schema, num_partitions=1)
    vals = [b.column(0)[0].as_py() for b in reader.execute(0, ctx)]
    assert vals == [0, 1, 2, 3]
    # the fetches really ran concurrently (completion order was scrambled)
    assert fetched_order != [0, 1, 2, 3]

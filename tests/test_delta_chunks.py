"""Chunk-set delta layout cache (ISSUE 19 tentpole A): parquet-backed
batch prepares persist one entry per (path, mtime, size, chunk_index)
beneath the mtime-free chunk_key_base, so a query over files ∪ {new}
re-prepares only the new file's chunks and loads every existing tile
byte-for-byte — plus the mid-append fail-closed bugfix (a file whose
identity moves between the stat and the read must not poison the store)."""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from ballista_tpu.config import BallistaConfig
from ballista_tpu.engine import ExecutionContext
from ballista_tpu.ops import kernels
from ballista_tpu.ops.runtime import delta_stats


def _reset_stage_caches():
    """Simulate a fresh process: drop the in-memory stage cache and its HBM
    reservations so the next query rebuilds stages from scratch."""
    from ballista_tpu.ops.runtime import release_stage_residency, reset_residency

    for stage in kernels._stage_cache.values():
        if stage not in (None, False):
            release_stage_residency(stage)
    kernels._stage_cache.clear()
    kernels._stage_cache_pins.clear()
    kernels._stage_latest.clear()
    reset_residency()


@pytest.fixture(autouse=True)
def _fresh_caches():
    _reset_stage_caches()
    delta_stats(reset=True)
    yield
    _reset_stage_caches()
    delta_stats(reset=True)


def _ctx(cache_dir):
    return ExecutionContext(
        BallistaConfig(
            {
                "ballista.executor.backend": "tpu",
                "ballista.tpu.layout_cache_dir": str(cache_dir),
                # several chunks per file so per-chunk addressing is real
                "ballista.batch.size": "4096",
            }
        )
    )


def _part(seed, n=10_000):
    """Low-cardinality shape -> the unrolled batches (chunked) path."""
    rng = np.random.default_rng(seed)
    return pa.table(
        {
            "g": pa.array([f"grp{i}" for i in rng.integers(0, 5, n)]),
            "v": pa.array(rng.integers(0, 1000, n), type=pa.int64()),
            "w": pa.array(rng.uniform(-10, 10, n)),
        }
    )


QUERY = (
    "select g, sum(v) as sv, count(*) as c, min(v) as mn from t "
    "where w > -5 group by g order by g"
)


def _run(data_dir, cache_dir):
    ctx = _ctx(cache_dir)
    ctx.register_parquet("t", str(data_dir))
    return ctx.sql(QUERY).collect()


def test_append_reprepares_only_new_chunks(tmp_path, monkeypatch):
    data = tmp_path / "data"
    data.mkdir()
    pq.write_table(_part(0), str(data / "part-0.parquet"))
    pq.write_table(_part(1), str(data / "part-1.parquet"))
    cache = tmp_path / "layouts"

    _run(data, cache)
    cold = delta_stats(reset=True)
    assert cold.get("chunks_prepared", 0) >= 2, cold
    assert cold.get("chunks_reused", 0) == 0, cold

    # append one file; the grown set must re-prepare ONLY its chunks
    pq.write_table(_part(2), str(data / "part-2.parquet"))
    _reset_stage_caches()

    from ballista_tpu.ops.stage import FusedAggregateStage

    real = FusedAggregateStage._read_scan_file

    def _guard(self, path, ctx):
        if "part-2" not in str(path):
            raise AssertionError(f"re-read of existing file {path}")
        return real(self, path, ctx)

    monkeypatch.setattr(FusedAggregateStage, "_read_scan_file", _guard)
    try:
        grown = _run(data, cache)
    finally:
        monkeypatch.setattr(FusedAggregateStage, "_read_scan_file", real)
    warm = delta_stats(reset=True)
    assert warm.get("chunks_reused", 0) >= cold["chunks_prepared"], warm
    assert warm.get("chunks_prepared", 0) >= 1, warm
    assert warm.get("bytes_reprepared_saved", 0) > 0, warm

    # bit-identity: the advanced prepare must equal a cold full run over
    # the grown set (fresh process, empty layout store)
    _reset_stage_caches()
    cold_grown = _run(data, tmp_path / "layouts-cold")
    assert grown.equals(cold_grown)


def test_warm_set_reuses_every_chunk(tmp_path, monkeypatch):
    """Unchanged file set: the second fresh process loads everything and
    never touches the parquet data pages at prepare time."""
    data = tmp_path / "data"
    data.mkdir()
    pq.write_table(_part(3), str(data / "part-0.parquet"))
    cache = tmp_path / "layouts"
    first = _run(data, cache)
    delta_stats(reset=True)
    _reset_stage_caches()

    from ballista_tpu.ops.stage import FusedAggregateStage

    def _no_read(self, path, ctx):
        raise AssertionError("parquet decode on a warm chunk set")

    real = FusedAggregateStage._read_scan_file
    monkeypatch.setattr(FusedAggregateStage, "_read_scan_file", _no_read)
    try:
        warm = _run(data, cache)
    finally:
        monkeypatch.setattr(FusedAggregateStage, "_read_scan_file", real)
    stats = delta_stats(reset=True)
    assert stats.get("chunks_reused", 0) >= 1, stats
    assert stats.get("chunks_prepared", 0) == 0, stats
    assert warm.equals(first)


def test_midappend_write_fails_closed(tmp_path):
    """ISSUE 19 bugfix regression: a writer whose file identity moved
    between the pre-read stat and the read must DECLINE the save — the
    decoded bytes may not be the state the identity describes, and
    persisting them poisons the entry for any process that fingerprints at
    the old identity. Pre-fix, this test fails with grp sums from the
    appended data served against the original file."""
    data = tmp_path / "data"
    data.mkdir()
    path = str(data / "part-0.parquet")
    t1 = _part(7)
    pq.write_table(t1, path)
    st1 = os.stat(path)
    cache = tmp_path / "layouts"

    t2 = pa.concat_tables([t1, _part(8, n=4_096)])

    from ballista_tpu.ops.stage import FusedAggregateStage

    real = FusedAggregateStage._read_scan_file

    def _mid_append(self, p, ctx):
        # the append lands after the prepare statted the file but before
        # (equivalently: during) the read — the read sees the NEW bytes
        pq.write_table(t2, p)
        return real(self, p, ctx)

    FusedAggregateStage._read_scan_file = _mid_append
    try:
        _run(data, cache)
    finally:
        FusedAggregateStage._read_scan_file = real
    stats = delta_stats(reset=True)
    assert stats.get("save_declined_midappend", 0) >= 1, stats

    # another process raced the same window: it fingerprinted at the OLD
    # identity and the file it reads is the OLD state (simulated by
    # restoring the original bytes + mtime). It must NOT be served the
    # torn writer's tiles.
    pq.write_table(t1, path)
    os.utime(path, (st1.st_atime, st1.st_mtime))
    assert os.stat(path).st_size == st1.st_size  # deterministic writer
    _reset_stage_caches()
    got = _run(data, cache)

    host = ExecutionContext(BallistaConfig({"ballista.executor.backend": "cpu"}))
    host.register_parquet("t", str(data))
    expected = host.sql(QUERY).collect()
    assert got.column("g").equals(expected.column("g"))
    assert got.column("sv").to_pylist() == expected.column("sv").to_pylist()
    assert got.column("c").to_pylist() == expected.column("c").to_pylist()


def test_tampered_chunk_identity_misses(tmp_path):
    """Load-side belt: an entry whose stamped identity does not match the
    identity its key was computed from is refused, and the file
    re-prepares (fail closed, never serve)."""
    import json

    data = tmp_path / "data"
    data.mkdir()
    pq.write_table(_part(9), str(data / "part-0.parquet"))
    cache = tmp_path / "layouts"
    first = _run(data, cache)
    delta_stats(reset=True)

    metas = list(cache.rglob("meta.json"))
    assert metas
    for mp in metas:
        m = json.load(open(mp))
        if m.get("kind") == "chunk":
            m["ident"] = [m["ident"][0], "0.0", 0]
            json.dump(m, open(mp, "w"))
    _reset_stage_caches()
    again = _run(data, cache)
    stats = delta_stats(reset=True)
    assert stats.get("chunks_reused", 0) == 0, stats
    assert stats.get("chunks_prepared", 0) >= 1, stats
    assert again.equals(first)

"""SPMD stage programs over the virtual 8-device CPU mesh."""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def mesh8():
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    from ballista_tpu.parallel.mesh import build_mesh

    return build_mesh({"data": 8})


def test_q1_style_psum_aggregate(mesh8):
    import jax.numpy as jnp

    from ballista_tpu.parallel.spmd import build_q1_style_step

    rng = np.random.default_rng(0)
    N, G = 4096, 6
    codes = rng.integers(0, G, N).astype(np.int32)
    qty = rng.uniform(1, 50, N).astype(np.float32)
    price = rng.uniform(900, 10_000, N).astype(np.float32)
    disc = rng.uniform(0, 0.1, N).astype(np.float32)
    tax = rng.uniform(0, 0.08, N).astype(np.float32)
    ship = rng.integers(8000, 10_500, N).astype(np.int32)

    step = build_q1_style_step(mesh8, G, cutoff_days=10_000)
    out = np.asarray(
        step(*(jnp.asarray(a) for a in (codes, qty, price, disc, tax, ship)))
    )
    assert out.shape == (6, G)

    m = ship <= 10_000
    ref_counts = np.zeros(G)
    np.add.at(ref_counts, codes[m], 1.0)
    np.testing.assert_allclose(out[0], ref_counts, rtol=1e-5)
    ref_qty = np.zeros(G)
    np.add.at(ref_qty, codes[m], qty[m])
    np.testing.assert_allclose(out[1], ref_qty, rtol=1e-4)
    ref_charge = np.zeros(G)
    np.add.at(ref_charge, codes[m], (price * (1 - disc) * (1 + tax))[m])
    np.testing.assert_allclose(out[4], ref_charge, rtol=1e-4)


def test_all_to_all_exchange_aggregate(mesh8):
    import jax.numpy as jnp

    from ballista_tpu.parallel.spmd import build_all_to_all_exchange_aggregate

    rng = np.random.default_rng(1)
    N, K = 4096, 64  # 64 keys over 8 shards -> 8 groups per shard
    keys = rng.integers(0, K, N).astype(np.int32)
    vals = rng.uniform(0, 1, N).astype(np.float32)

    ex = build_all_to_all_exchange_aggregate(mesh8)
    sums = np.asarray(ex(jnp.asarray(keys), jnp.asarray(vals), K // 8))

    ref = np.zeros(K)
    np.add.at(ref, keys, vals)
    # shard d owns keys with key % 8 == d, local group id = key // 8
    got_global = np.zeros(K)
    per_shard = sums.reshape(8, K // 8)
    for d in range(8):
        for g in range(K // 8):
            got_global[g * 8 + d] = per_shard[d, g]
    np.testing.assert_allclose(got_global, ref, rtol=1e-4)


def test_mesh_build_defaults():
    from ballista_tpu.parallel.mesh import build_mesh

    m = build_mesh()
    assert "data" in m.shape


def test_dryrun_multichip_inprocess():
    import pathlib
    import sys

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    import __graft_entry__ as g

    g.dryrun_multichip(8)


def test_dryrun_multichip_self_forces_platform():
    """The driver calls dryrun_multichip in a process with ONE device; the
    entry must force the virtual multi-device CPU platform itself
    (MULTICHIP_r01 regression)."""
    import os
    import pathlib
    import subprocess
    import sys

    root = pathlib.Path(__file__).resolve().parent.parent
    env = dict(os.environ)
    # child sees a 1-device CPU platform, like the driver's bare process;
    # drop the axon vars so the child can't touch the TPU relay (hermetic)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("PALLAS_AXON_REMOTE_COMPILE", None)
    code = (
        f"import sys; sys.path.insert(0, {str(root)!r})\n"
        "import jax\n"
        "assert len(jax.devices()) == 1, jax.devices()\n"
        "import __graft_entry__ as g\n"
        "g.dryrun_multichip(4)\n"
    )
    subprocess.run([sys.executable, "-c", code], env=env, check=True, cwd=root)

"""Regression tests for the round-5 advisor findings (ADVICE r5): zero-row
inner attachments, persisted-layout eligibility for non-file-backed stages,
the multi-host read/lower fence, the pod guard on the mesh join, and bool
allgather normalization."""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from ballista_tpu.config import BallistaConfig
from ballista_tpu.engine import ExecutionContext
from ballista_tpu.ops import kernels
from ballista_tpu.ops.runtime import UnsupportedOnDevice
from ballista_tpu.physical.plan import TaskContext


def _reset_stage_caches():
    from ballista_tpu.ops.runtime import release_stage_residency, reset_residency

    for stage in kernels._stage_cache.values():
        if stage not in (None, False):
            release_stage_residency(stage)
    kernels._stage_cache.clear()
    kernels._stage_cache_pins.clear()
    kernels._stage_latest.clear()
    reset_residency()


@pytest.fixture(autouse=True)
def _fresh():
    _reset_stage_caches()
    yield
    _reset_stage_caches()


# -- ops/mappedscan.py: zero-row INNER attachment ---------------------------


def test_zero_row_inner_attachment_declines_to_host(tmp_path):
    """An empty inner dim must decline (UnsupportedOnDevice), not IndexError
    through _extend's empty gather; the host path returns the correct empty
    result. The dim-valued aggregate input keeps factagg out of the way, so
    the mapped rewrite owns this shape."""
    n = 3000
    fact = pa.table(
        {
            "fk": pa.array(np.arange(n) % 50, type=pa.int64()),
            "mode": pa.array([f"m{i % 4}" for i in range(n)]),
            "amount": pa.array(np.linspace(0.0, 1.0, n)),
        }
    )
    dim = pa.table(
        {
            "dk": pa.array([], type=pa.int64()),
            "prio": pa.array([], type=pa.string()),
        }
    )
    fp, dp = str(tmp_path / "fact.parquet"), str(tmp_path / "dim.parquet")
    pq.write_table(fact, fp)
    pq.write_table(dim, dp)
    sql = (
        "select mode, sum(case when prio = 'p0' then 1 else 0 end) as c0, "
        "sum(amount) as s from dim, fact where dk = fk group by mode"
    )
    outs = {}
    for backend in ("cpu", "tpu"):
        ctx = ExecutionContext(
            BallistaConfig({"ballista.executor.backend": backend})
        )
        ctx.register_parquet("fact", fp)
        ctx.register_parquet("dim", dp)
        outs[backend] = ctx.sql(sql).collect()
    assert outs["cpu"].num_rows == 0
    assert outs["tpu"].num_rows == 0
    assert outs["tpu"].schema == outs["cpu"].schema


# -- ops/kernels.py: persisted-layout eligibility ---------------------------


def _shuffle_fed_aggregate(tmp_path, schema):
    """A PARTIAL aggregate whose leaf is a ShuffleReaderExec over one local
    piece — a stage whose data identity is NOT file-backed."""
    from ballista_tpu.distributed.stages import ShuffleLocation, ShuffleReaderExec
    from ballista_tpu.physical import expr as px
    from ballista_tpu.physical.aggregate import (
        AggregateFunc,
        AggregateMode,
        HashAggregateExec,
    )

    base = tmp_path / "map0"
    base.mkdir(parents=True, exist_ok=True)
    piece = base / "0.arrow"
    with pa.ipc.new_file(str(piece), schema) as w:
        w.write_batch(
            pa.record_batch(
                [
                    pa.array([1, 1, 2, 2], type=pa.int64()),
                    pa.array([1.0, 2.0, 3.0, 4.0]),
                ],
                schema=schema,
            )
        )
    reader = ShuffleReaderExec(
        [ShuffleLocation("e0", "localhost", 50050, str(base))],
        schema,
        num_partitions=1,
    )
    agg = HashAggregateExec(
        AggregateMode.PARTIAL,
        reader,
        [(px.ColumnExpr("g", 0), "g")],
        [AggregateFunc("sum", px.ColumnExpr("v", 1), "s", pa.float64(), pa.float64())],
    )
    return agg, piece


def test_shuffle_fed_stage_never_persists(tmp_path):
    """A stage fed by a shuffle reader carries no file mtimes in its cache
    key: persisting its layout could serve stale tiles after the shuffle
    data changes. persist_key must stay None and no entry may be written."""
    schema = pa.schema([pa.field("g", pa.int64()), pa.field("v", pa.float64())])
    agg, _piece = _shuffle_fed_aggregate(tmp_path, schema)
    cache_dir = tmp_path / "layouts"
    cfg = BallistaConfig(
        {
            "ballista.executor.backend": "tpu",
            "ballista.tpu.fuse_volatile_sources": "true",
            "ballista.tpu.layout_cache_dir": str(cache_dir),
        }
    )
    out = kernels.hash_aggregate(agg, 0, TaskContext(config=cfg))
    assert out is not None and out.num_rows > 0
    stages = [s for s in kernels._stage_cache.values() if s not in (None, False)]
    assert stages, "device stage did not build"
    assert all(s.persist_key is None for s in stages)
    assert not cache_dir.exists() or not any(cache_dir.rglob("*"))


def test_layout_cache_misses_after_file_mtime_change(tmp_path):
    """File-backed stages DO persist — and a rewritten file (new mtime) must
    miss the cache and produce the new data's results in a fresh process."""
    path = str(tmp_path / "t.parquet")
    cache = str(tmp_path / "layouts")

    def write(mult, when):
        pq.write_table(
            pa.table(
                {
                    "g": pa.array([1, 1, 2, 2] * 500, type=pa.int64()),
                    "v": pa.array([float(mult)] * 2000),
                }
            ),
            path,
        )
        os.utime(path, (when, when))

    def run():
        ctx = ExecutionContext(
            BallistaConfig(
                {
                    "ballista.executor.backend": "tpu",
                    "ballista.tpu.layout_cache_dir": cache,
                }
            )
        )
        ctx.register_parquet("t", path)
        out = ctx.sql("select g, sum(v) as s from t group by g").collect()
        return dict(zip(out.column("g").to_pylist(), out.column("s").to_pylist()))

    t0 = os.stat(tmp_path).st_mtime
    write(1, t0)
    assert run() == {1: 1000.0, 2: 1000.0}
    write(3, t0 + 60)  # rewritten data, strictly newer mtime
    _reset_stage_caches()  # fresh process: only the DISK cache survives
    assert run() == {1: 3000.0, 2: 3000.0}


# -- parallel/spmd_stage.py: multi-host read/lower fence --------------------


def _spmd_aggregate():
    from ballista_tpu.distributed.planner import DistributedPlanner
    from ballista_tpu.logical import col, functions as F
    from ballista_tpu.parallel.spmd_stage import SpmdAggregateExec

    cfg = BallistaConfig(
        {
            "ballista.executor.backend": "tpu",
            "ballista.tpu.spmd_stages": "true",
            "ballista.tpu.mesh": "data:8",
        }
    )
    ctx = ExecutionContext(cfg)
    rng = np.random.default_rng(2)
    ctx.register_record_batches(
        "t",
        pa.table(
            {
                "g": pa.array(rng.integers(0, 4, 400), type=pa.int64()),
                "v": pa.array(rng.uniform(0, 1, 400)),
            }
        ),
        n_partitions=4,
    )
    df = ctx.table("t").aggregate([col("g")], [F.sum(col("v")).alias("s")])
    phys = ctx.create_physical_plan(df.logical_plan())
    stages = DistributedPlanner(cfg).plan_query_stages("job", phys)

    def find(n):
        if isinstance(n, SpmdAggregateExec):
            return n
        for c in n.children():
            r = find(c)
            if r is not None:
                return r
        return None

    spmd = next((j for j in (find(s) for s in stages) if j is not None), None)
    assert spmd is not None, "planner did not fuse the aggregate"
    return spmd, cfg


@pytest.mark.parametrize(
    "exc",
    [
        OSError("parquet file vanished mid-read"),
        MemoryError("decode OOM"),
        pa.ArrowInvalid("Parquet magic bytes not found"),
    ],
    ids=["oserror", "memoryerror", "arrowinvalid"],
)
def test_multihost_fence_declines_host_failures_collectively(monkeypatch, exc):
    """A host-side failure during this host's reads (missing file, decode
    OOM, corrupt parquet — ArrowInvalid subclasses ValueError, not OSError)
    must flow into the COLLECTIVE agree(False) decline — not escape the
    fence and leave peers blocked in the allgather."""
    from ballista_tpu.ops.stage import FusedAggregateStage
    from ballista_tpu.parallel import multihost as mh

    spmd, cfg = _spmd_aggregate()
    tctx = TaskContext(config=cfg)
    stage = FusedAggregateStage(spmd.partial)
    mesh = spmd._build_mesh(tctx)
    n_dev = int(np.prod(list(mesh.shape.values())))

    agreed = []

    def fake_agree(ok):
        agreed.append(ok)
        return ok

    def boom(n_parts, mesh):
        raise exc

    monkeypatch.setattr(mh, "agree", fake_agree)
    monkeypatch.setattr(mh, "owned_partitions", boom)
    with pytest.raises(UnsupportedOnDevice, match="declined collectively"):
        spmd._execute_mesh_multihost(tctx, stage, mesh, n_dev)
    assert agreed == [False]


# -- parallel/spmd_join.py: pod guard ---------------------------------------


def test_mesh_join_declines_on_multi_process(monkeypatch):
    """collect_all reads host-LOCAL rows; on a pod the mesh spans every
    process, so feeding those arrays to a global shard_map is wrong — the
    mesh join must decline to the host join when process_count > 1."""
    import jax

    from ballista_tpu.distributed.planner import DistributedPlanner
    from ballista_tpu.parallel.spmd_join import SpmdJoinExec

    cfg = BallistaConfig(
        {
            "ballista.executor.backend": "tpu",
            "ballista.tpu.spmd_stages": "true",
            "ballista.tpu.mesh": "data:8",
        }
    )
    ctx = ExecutionContext(cfg)
    ctx.register_record_batches(
        "l",
        pa.table({"dk": pa.array(range(50), type=pa.int64())}),
        n_partitions=2,
    )
    ctx.register_record_batches(
        "r",
        pa.table({"fk": pa.array([i % 50 for i in range(200)], type=pa.int64()),
                  "v": pa.array(np.arange(200, dtype=np.float64))}),
        n_partitions=2,
    )
    df = ctx.table("l").join(ctx.table("r"), ["dk"], ["fk"], how="inner")
    phys = ctx.create_physical_plan(df.logical_plan())
    stages = DistributedPlanner(cfg).plan_query_stages("job", phys)

    def find(n):
        if isinstance(n, SpmdJoinExec):
            return n
        for c in n.children():
            r = find(c)
            if r is not None:
                return r
        return None

    spmd = next((j for j in (find(s) for s in stages) if j is not None), None)
    assert spmd is not None, "planner did not fuse the join"
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    with pytest.raises(UnsupportedOnDevice, match="single-host"):
        spmd._execute_mesh(TaskContext(config=cfg, work_dir="/tmp", job_id="t"))


# -- parallel/multihost.py: bool allgather ----------------------------------


def test_allgather_rows_normalizes_bool_to_int64():
    from ballista_tpu.parallel import multihost as mh

    out = mh.allgather_rows(np.array([True, False, True]))
    assert out.dtype == np.int64
    assert out.tolist() == [1, 0, 1]

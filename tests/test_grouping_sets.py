"""ROLLUP / CUBE / GROUPING SETS (lowered to a UNION ALL of one aggregation
per grouping set; excluded keys project as typed NULLs)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from ballista_tpu.engine import ExecutionContext
from ballista_tpu.errors import BallistaError, SqlError


@pytest.fixture
def ctx():
    c = ExecutionContext()
    rng = np.random.default_rng(7)
    t = pa.table(
        {
            "r": pa.array(rng.choice(["east", "west", "north"], 200).tolist()),
            "p": pa.array(rng.choice(["a", "b", "c", "d"], 200).tolist()),
            "v": pa.array(np.round(rng.uniform(0, 100, 200), 2)),
            "q": pa.array(rng.integers(1, 20, 200), type=pa.int64()),
        }
    )
    c.register_record_batches("s", t)
    return c, t.to_pandas()


def _rollup_oracle(df, keys, agg_col="v"):
    frames = []
    for k in range(len(keys), -1, -1):
        sub = keys[:k]
        if sub:
            g = df.groupby(sub, as_index=False).agg(s=(agg_col, "sum"), n=(agg_col, "count"))
        else:
            g = pd.DataFrame({"s": [df[agg_col].sum()], "n": [len(df)]})
        for missing in keys[k:]:
            g[missing] = None
        frames.append(g[keys + ["s", "n"]])
    return pd.concat(frames, ignore_index=True)


def test_rollup_matches_pandas(ctx):
    c, df = ctx
    out = (
        c.sql("select r, p, sum(v) as s, count(v) as n from s "
              "group by rollup(r, p) order by r, p")
        .collect().to_pandas()
    )
    exp = (
        _rollup_oracle(df, ["r", "p"])
        .sort_values(["r", "p"], na_position="last")
        .reset_index(drop=True)
    )
    assert out["r"].fillna("~").tolist() == exp["r"].fillna("~").tolist()
    assert out["p"].fillna("~").tolist() == exp["p"].fillna("~").tolist()
    np.testing.assert_allclose(out["s"].to_numpy(), exp["s"].to_numpy(), rtol=1e-9)
    assert out["n"].tolist() == exp["n"].tolist()


def test_cube_counts(ctx):
    c, df = ctx
    out = c.sql("select r, p, sum(q) as s from s group by cube(r, p)").collect()
    nr, np_ = df["r"].nunique(), df["p"].nunique()
    pairs = df.groupby(["r", "p"]).ngroups
    assert out.num_rows == pairs + nr + np_ + 1
    # grand total row
    tot = [s for r, p, s in zip(out.column("r").to_pylist(),
                                out.column("p").to_pylist(),
                                out.column("s").to_pylist())
           if r is None and p is None]
    assert tot == [df["q"].sum()]


def test_grouping_sets_explicit(ctx):
    c, df = ctx
    out = (
        c.sql("select r, p, sum(v) as s from s "
              "group by grouping sets ((r, p), (p), ()) order by p, r")
        .collect()
    )
    assert out.num_rows == df.groupby(["r", "p"]).ngroups + df["p"].nunique() + 1


def test_rollup_with_having_and_exprs(ctx):
    c, df = ctx
    out = (
        c.sql("select r, sum(v) as s from s group by rollup(r) "
              "having sum(v) > 0 order by s desc limit 2")
        .collect()
    )
    # grand total is the largest
    np.testing.assert_allclose(out.column("s").to_pylist()[0], df["v"].sum(), rtol=1e-9)


def test_rollup_rejects_star(ctx):
    c, _ = ctx
    with pytest.raises(BallistaError):
        c.sql("select * from s group by rollup(r)")


def test_super_aggregate_counts_real_column(ctx):
    """count(r) in the grand-total row counts every non-null r — the NULL
    substitution must not reach aggregate arguments (review regression)."""
    c, df = ctx
    out = c.sql("select r, count(r) as n from s group by rollup(r) order by r").collect()
    assert out.column("n").to_pylist()[-1] == len(df)


def test_rollup_composes_with_union(ctx):
    c, df = ctx
    n_groups = df["r"].nunique()
    u1 = c.sql("select r, sum(v) as s from s group by rollup(r) "
               "union all select 'X' as r, 99.0 as s").collect()
    assert u1.num_rows == n_groups + 2
    u2 = c.sql("select 'X' as r, 99.0 as s union all "
               "select r, sum(v) as s from s group by rollup(r)").collect()
    assert u2.num_rows == n_groups + 2


def test_order_by_aggregate_expr_over_rollup(ctx):
    c, df = ctx
    out = c.sql("select r, sum(v) as s from s group by rollup(r) order by sum(v) desc").collect()
    np.testing.assert_allclose(out.column("s").to_pylist()[0], df["v"].sum(), rtol=1e-9)


def test_nonreserved_keywords_stay_identifiers(ctx):
    """Columns named cube/sets/rows remain addressable (the lexer reserves
    them only as clause introducers)."""
    c, _ = ctx
    t = pa.table({"cube": pa.array([2, 1]), "sets": pa.array([3, 4]),
                  "rows": pa.array([5, 6])})
    c.register_record_batches("kw", t)
    out = c.sql("select cube, sets, rows from kw order by cube").collect()
    assert out.column("cube").to_pylist() == [1, 2]
    assert out.column("rows").to_pylist() == [6, 5]


def test_fromless_select_produces_one_row(ctx):
    c, _ = ctx
    out = c.sql("select 1 as a, 'x' as b").collect()
    assert out.num_rows == 1
    assert out.column("a").to_pylist() == [1]


def test_grouping_marker_function(ctx):
    """GROUPING(key) = 1 on rows where the key is aggregated away."""
    c, df = ctx
    out = (
        c.sql("select r, grouping(r) as gr, sum(v) as s from s "
              "group by rollup(r) order by gr, r")
        .collect()
    )
    n = df["r"].nunique()
    assert out.column("gr").to_pylist() == [0] * n + [1]
    assert out.column("r").to_pylist()[-1] is None
    # usable in HAVING to drop super-aggregate rows
    out2 = c.sql(
        "select r, sum(v) as s from s group by rollup(r) "
        "having grouping(r) = 0 order by r"
    ).collect()
    assert out2.num_rows == n and None not in out2.column("r").to_pylist()


def test_grouping_marker_plain_group_by_and_errors(ctx):
    c, df = ctx
    out = c.sql("select r, grouping(r) as g from s group by r order by r").collect()
    assert set(out.column("g").to_pylist()) == {0}
    from ballista_tpu.errors import BallistaError

    with pytest.raises(BallistaError):
        c.sql("select grouping(v) as g from s group by r")
    with pytest.raises(BallistaError):
        c.sql("select grouping(r) as g from s")

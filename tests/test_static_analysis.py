"""ballista-lint (dev/analysis): the analyzer itself is tier-1 — a clean
self-run over ballista_tpu/ gates the tree, each rule is exercised against
known-bad and known-good fixture snippets, and the suppression syntax
(mandatory reasons) plus per-file cache behavior are pinned."""

import json
import os
import pathlib
import shutil
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
FIXTURES = pathlib.Path(__file__).resolve().parent / "fixtures" / "lint"

sys.path.insert(0, str(REPO))

from dev.analysis.core import (  # noqa: E402
    RULE_NAMES,
    analyze_file,
    run_paths,
)

RULES = [
    "readback-discipline",
    "tracer-hygiene",
    "dtype-discipline",
    "guarded-by",
    "decline-discipline",
    "failure-discipline",
    "routing-discipline",
    "durability",
]


def _rules_hit(path) -> set:
    return {f.rule for f in analyze_file(str(path))}


# -- the gate: the production tree is clean ---------------------------------

def test_self_run_clean_over_package():
    findings, stats = run_paths([str(REPO / "ballista_tpu")], use_cache=False)
    assert findings == [], "\n".join(f.format() for f in findings)
    # ISSUE 3 acceptance: at most 5 reasoned suppressions in the package
    assert stats["suppressions"] <= 5
    assert stats["files"] > 50  # actually swept the tree


def test_all_rules_registered():
    names = RULE_NAMES()
    for r in RULES:
        assert r in names
    assert "lock-order" in names  # ISSUE 14
    assert "durability" in names  # ISSUE 18
    assert "lint-usage" in names


# -- lock-order (ISSUE 14) ---------------------------------------------------

def test_lockorder_fixture_pair():
    """ISSUE 14: an undeclared nesting acquired in both orders (a cycle),
    a raw unwitnessable threading.Lock, a missing annotation, and a lying
    make_lock literal all fail lint; the canonical shapes (declared
    forward nesting, holds-lock helper, double-checked insert, annotated
    check-then-act) are clean."""
    findings = [
        f.message for f in analyze_file(str(FIXTURES / "lockorder_bad.py"))
        if f.rule == "lock-order"
    ]
    assert any("undeclared lock-order edge" in m for m in findings), findings
    assert any("potential deadlock: lock-order cycle" in m for m in findings)
    assert any("raw threading.Lock()" in m for m in findings)
    assert any("no guarded-by:/holds-lock: annotation" in m for m in findings)
    assert any("does not match its canonical identity" in m for m in findings)
    good = analyze_file(str(FIXTURES / "lockorder_good.py"))
    assert good == [], "\n".join(f.format() for f in good)


def test_atomicity_fixture_flagged():
    """ISSUE 14: a read-modify-write of guarded state spanning two
    acquisitions (check-then-act across a release) fails lint."""
    findings = [
        f.message for f in analyze_file(str(FIXTURES / "atomicity_bad.py"))
        if f.rule == "lock-order"
    ]
    assert any("check-then-act across a release" in m for m in findings)


# -- per-rule fixtures -------------------------------------------------------

@pytest.mark.parametrize("rule", RULES)
def test_bad_fixture_flags_its_rule(rule):
    stem = rule.split("-")[0]
    hit = _rules_hit(FIXTURES / f"{stem}_bad.py")
    assert rule in hit, f"{rule} did not fire on its bad fixture (hit: {hit})"


@pytest.mark.parametrize("rule", RULES)
def test_good_fixture_is_clean(rule):
    stem = rule.split("-")[0]
    findings = analyze_file(str(FIXTURES / f"{stem}_good.py"))
    assert findings == [], "\n".join(f.format() for f in findings)


def test_bad_fixtures_fail_via_cli():
    """Acceptance: `python -m dev.analysis` exits nonzero on each bad
    fixture (one CLI invocation per file, as CI would run it)."""
    for bad in sorted(FIXTURES.glob("*_bad.py")):
        proc = subprocess.run(
            [sys.executable, "-m", "dev.analysis", str(bad), "--no-cache"],
            cwd=str(REPO), capture_output=True, text=True,
        )
        assert proc.returncode == 1, (bad, proc.stdout, proc.stderr)


def test_tracer_rule_walks_call_graph():
    """The decoration site is `jax.jit(wrapped)`; the violation lives in a
    helper `wrapped` calls — the walk must reach it."""
    findings = analyze_file(str(FIXTURES / "tracer_bad.py"))
    assert any(
        f.rule == "tracer-hygiene" and "'helper'" in f.message for f in findings
    ), "\n".join(f.format() for f in findings)


def test_decline_rule_flags_all_three_shapes():
    findings = [
        f.message for f in analyze_file(str(FIXTURES / "decline_bad.py"))
        if f.rule == "decline-discipline"
    ]
    assert any("without a reason" in m for m in findings)
    assert any("ad-hoc" in m for m in findings)
    assert any("return None" in m for m in findings)


def test_overflow_decline_fixture_pair():
    """The M:N join tier-overflow decline site (ISSUE 4): a reasonless
    overflow raise / silent None is flagged; the canonical
    join_multiplicity_tier + step_aside + record_join_path shape is clean."""
    findings = [
        f.message
        for f in analyze_file(str(FIXTURES / "decline_overflow_bad.py"))
        if f.rule == "decline-discipline"
    ]
    assert any("without a reason" in m for m in findings)
    assert any("return None" in m for m in findings)
    good = analyze_file(str(FIXTURES / "decline_overflow_good.py"))
    assert good == [], "\n".join(f.format() for f in good)


def test_failure_rule_flags_all_four_shapes():
    """ISSUE 5 satellite: anonymous fetch_failed, unregistered site,
    computed site, ad-hoc ChaosInjected raise."""
    findings = [
        f.message for f in analyze_file(str(FIXTURES / "failure_bad.py"))
        if f.rule == "failure-discipline"
    ]
    assert any("lost location" in m for m in findings)
    assert any("unregistered chaos site" in m for m in findings)
    assert any("string literal" in m for m in findings)
    assert any("ad-hoc" in m and "ChaosInjected" in m for m in findings)


def test_failure_rule_scheduler_site_fixture_pair():
    """ISSUE 6 satellite: unregistered or computed (non-literal) chaos site
    names in SCHEDULER code fail lint; the registered-literal plan-write /
    crash shapes are clean."""
    findings = [
        f.message
        for f in analyze_file(str(FIXTURES / "failure_sched_bad.py"))
        if f.rule == "failure-discipline"
    ]
    assert any(
        "unregistered chaos site" in m and "scheduler.plan_commit" in m
        for m in findings
    ), findings
    assert any("string literal" in m for m in findings), findings
    good = analyze_file(str(FIXTURES / "failure_sched_good.py"))
    assert good == [], "\n".join(f.format() for f in good)


def test_failure_rule_tenancy_site_fixture_pair():
    """ISSUE 7 satellite: the new cache.put / scheduler.admit sites are
    registered — unregistered cache sites and computed admission site names
    in the tenancy code fail lint; the registered-literal shapes are clean."""
    findings = [
        f.message
        for f in analyze_file(str(FIXTURES / "failure_tenancy_bad.py"))
        if f.rule == "failure-discipline"
    ]
    assert any(
        "unregistered chaos site" in m and "cache.write" in m
        for m in findings
    ), findings
    assert any("string literal" in m for m in findings), findings
    good = analyze_file(str(FIXTURES / "failure_tenancy_good.py"))
    assert good == [], "\n".join(f.format() for f in good)


def test_failure_rule_push_site_fixture_pair():
    """ISSUE 8 satellite: the new scheduler.push / aot.load sites are
    registered — an unregistered push-stream site and a computed AOT-load
    site name in latency-tier code fail lint; the registered-literal shapes
    are clean."""
    findings = [
        f.message
        for f in analyze_file(str(FIXTURES / "failure_push_bad.py"))
        if f.rule == "failure-discipline"
    ]
    assert any(
        "unregistered chaos site" in m and "scheduler.stream" in m
        for m in findings
    ), findings
    assert any("string literal" in m for m in findings), findings
    good = analyze_file(str(FIXTURES / "failure_push_good.py"))
    assert good == [], "\n".join(f.format() for f in good)


def test_failure_rule_speculation_fixture_pair():
    """ISSUE 11 satellite: speculation discipline — a minted duplicate
    attempt (`.speculative = True`) with no same-scope durable ledger
    record (_spec_put / _ledger_put) fails lint, as does the unregistered
    straggler chaos site; the ledgered mint, the ledgered promotion, the
    non-literal echo site, and the registered `task.slow` literal are
    clean."""
    findings = [
        f.message
        for f in analyze_file(str(FIXTURES / "failure_spec_bad.py"))
        if f.rule == "failure-discipline"
    ]
    assert any("ad-hoc speculative attempt" in m for m in findings), findings
    assert any(
        "unregistered chaos site" in m and "task.straggle" in m
        for m in findings
    ), findings
    good = analyze_file(str(FIXTURES / "failure_spec_good.py"))
    assert good == [], "\n".join(f.format() for f in good)


def test_failure_rule_batch_site_fixture_pair():
    """ISSUE 13 satellite: the new scheduler.batch site is registered — an
    unregistered grouping site and a computed site name in batching code
    fail lint; the registered-literal shape (generation-rotated sequence
    key) is clean."""
    findings = [
        f.message
        for f in analyze_file(str(FIXTURES / "failure_batch_bad.py"))
        if f.rule == "failure-discipline"
    ]
    assert any(
        "unregistered chaos site" in m and "scheduler.group" in m
        for m in findings
    ), findings
    assert any("string literal" in m for m in findings), findings
    good = analyze_file(str(FIXTURES / "failure_batch_good.py"))
    assert good == [], "\n".join(f.format() for f in good)


def test_failure_rule_fleet_site_fixture_pair():
    """ISSUE 15: the new shuffle.store and fleet.scale sites are
    registered — an unregistered storage site and a computed fleet site
    name fail lint; the registered-literal shapes (plan-coordinate keys on
    the storage seams, evaluation-sequence key on the scale decision) are
    clean."""
    findings = [
        f.message
        for f in analyze_file(str(FIXTURES / "failure_fleet_bad.py"))
        if f.rule == "failure-discipline"
    ]
    assert any(
        "unregistered chaos site" in m and "shuffle.publish" in m
        for m in findings
    ), findings
    assert any("string literal" in m for m in findings), findings
    good = analyze_file(str(FIXTURES / "failure_fleet_good.py"))
    assert good == [], "\n".join(f.format() for f in good)


def test_failure_rule_exchange_site_fixture_pair():
    """ISSUE 16: the exchange.evict site is registered — an unregistered
    exchange site and a computed exchange site name fail lint; the
    registered-literal shape (plan-coordinate + consuming-attempt key on
    the residency probe) is clean."""
    findings = [
        f.message
        for f in analyze_file(str(FIXTURES / "failure_exchange_bad.py"))
        if f.rule == "failure-discipline"
    ]
    assert any(
        "unregistered chaos site" in m and "exchange.drop" in m
        for m in findings
    ), findings
    assert any("string literal" in m for m in findings), findings
    good = analyze_file(str(FIXTURES / "failure_exchange_good.py"))
    assert good == [], "\n".join(f.format() for f in good)


def test_failure_rule_delta_site_fixture_pair():
    """ISSUE 19: the cache.advance site is registered — an unregistered
    advancement site and a computed cache site name fail lint; the
    registered-literal shape (result-key-keyed verdict BEFORE any KV
    write of the advanced entry) is clean."""
    findings = [
        f.message
        for f in analyze_file(str(FIXTURES / "failure_delta_bad.py"))
        if f.rule == "failure-discipline"
    ]
    assert any(
        "unregistered chaos site" in m and "cache.fold" in m
        for m in findings
    ), findings
    assert any("string literal" in m for m in findings), findings
    good = analyze_file(str(FIXTURES / "failure_delta_good.py"))
    assert good == [], "\n".join(f.format() for f in good)


def test_failure_rule_replica_site_fixture_pair():
    """ISSUE 20: the scheduler.lease and kv.lease sites are registered —
    an unregistered renewal site and a computed lease site name fail lint;
    the registered-literal shapes (generation/round-keyed verdicts BEFORE
    any lease write) are clean."""
    findings = [
        f.message
        for f in analyze_file(str(FIXTURES / "failure_replica_bad.py"))
        if f.rule == "failure-discipline"
    ]
    assert any(
        "unregistered chaos site" in m and "scheduler.renew" in m
        for m in findings
    ), findings
    assert any("string literal" in m for m in findings), findings
    good = analyze_file(str(FIXTURES / "failure_replica_good.py"))
    assert good == [], "\n".join(f.format() for f in good)


def test_routing_rule_fixture_pair():
    """ISSUE 10 satellite: a decline-helper call with no routing
    observation in scope and no cold-path annotation fails lint — a
    FOREIGN .observe() method included (only the qualified
    costmodel.observe counts); the recorder-paired and annotated shapes
    are clean, covering each accepted recorder (record_routing /
    record_routing_event / record_join_path / costmodel.observe)."""
    findings = [
        f for f in analyze_file(str(FIXTURES / "routing_bad.py"))
        if f.rule == "routing-discipline"
    ]
    assert len(findings) == 3, "\n".join(f.format() for f in findings)
    assert {f.line for f in findings} == {10, 14, 19}
    good = analyze_file(str(FIXTURES / "routing_good.py"))
    assert good == [], "\n".join(f.format() for f in good)


def test_routing_rule_skips_helper_definitions():
    """The canonical helpers in ops/kernels.py ARE the decline channel;
    their own bodies must not be flagged (and the production kernels module
    stays clean under the rule)."""
    findings = [
        f for f in analyze_file(str(REPO / "ballista_tpu" / "ops" / "kernels.py"))
        if f.rule == "routing-discipline"
    ]
    assert findings == [], "\n".join(f.format() for f in findings)


def test_failure_rule_sites_track_chaos_registry():
    """The rule reads SITES from ballista_tpu/utils/chaos.py, so the two
    can't drift silently."""
    from ballista_tpu.utils import chaos
    from dev.analysis.rules_failure import _registered_sites

    assert _registered_sites(str(REPO / "ballista_tpu" / "executor" /
                                 "execution_loop.py")) == frozenset(chaos.SITES)


def test_guarded_rule_checks_holds_lock_callers():
    findings = [
        f.message for f in analyze_file(str(FIXTURES / "guarded_bad.py"))
        if f.rule == "guarded-by"
    ]
    assert any("requires holding" in m for m in findings)
    assert any("accessed outside" in m for m in findings)


# -- suppressions ------------------------------------------------------------

def test_suppression_with_reason_suppresses():
    findings = analyze_file(str(FIXTURES / "suppress_ok.py"))
    assert findings == [], "\n".join(f.format() for f in findings)


def test_suppression_without_reason_rejected():
    findings = analyze_file(str(FIXTURES / "suppress_noreason.py"))
    rules = {f.rule for f in findings}
    assert "lint-usage" in rules  # the reasonless directive is itself flagged
    assert "readback-discipline" in rules  # and it did NOT suppress


def test_unused_suppression_flagged(tmp_path):
    p = tmp_path / "unused.py"
    p.write_text(
        "# ballista-lint: path=ballista_tpu/ops/fixture_unused.py\n"
        "x = 1  # ballista-lint: disable=readback-discipline -- nothing here\n"
    )
    findings = analyze_file(str(p))
    assert any(
        f.rule == "lint-usage" and "unused suppression" in f.message
        for f in findings
    )


def test_unknown_rule_in_suppression_flagged(tmp_path):
    p = tmp_path / "unknown.py"
    p.write_text("x = 1  # ballista-lint: disable=no-such-rule -- why\n")
    findings = analyze_file(str(p))
    assert any(
        f.rule == "lint-usage" and "unknown rule" in f.message for f in findings
    )


# -- CLI / cache / json ------------------------------------------------------

def test_json_output_and_cache_roundtrip(tmp_path):
    work = tmp_path / "pkg" / "ballista_tpu" / "ops"
    work.mkdir(parents=True)
    shutil.copy(FIXTURES / "readback_bad.py", work / "mod.py")
    cache = tmp_path / "cache.json"

    def run():
        proc = subprocess.run(
            [sys.executable, "-m", "dev.analysis", str(work), "--json",
             "--cache-file", str(cache)],
            cwd=str(REPO), capture_output=True, text=True,
        )
        return proc.returncode, json.loads(proc.stdout)

    rc1, out1 = run()
    assert rc1 == 1 and not out1["ok"]
    assert out1["stats"]["cache_hits"] == 0
    assert {f["rule"] for f in out1["findings"]} == {"readback-discipline"}
    assert all(
        {"rule", "path", "line", "col", "message"} <= set(f) for f in out1["findings"]
    )

    rc2, out2 = run()  # warm: same findings, served from cache
    assert rc2 == 1
    assert out2["stats"]["cache_hits"] == out2["stats"]["files"] == 1
    assert out2["findings"] == out1["findings"]

    # an edit invalidates the entry and flips the verdict
    text = (work / "mod.py").read_text().replace(
        "return np.asarray(out)  # unrecorded d2h transfer",
        "from ballista_tpu.ops.runtime import record_readback\n"
        "    arr = np.asarray(out)\n"
        "    record_readback(arr.shape[-1], arr.nbytes)\n"
        "    return arr",
    ).replace(
        "return np.asarray(run(cols, aux))  # unrecorded d2h transfer",
        "from ballista_tpu.ops.runtime import readback\n"
        "    return readback(run(cols, aux))",
    )
    (work / "mod.py").write_text(text)
    os.utime(work / "mod.py")
    rc3, out3 = run()
    assert rc3 == 0 and out3["ok"], out3["findings"]


def test_manifest_edit_invalidates_per_file_cache(tmp_path):
    """ISSUE 18 satellite: per-file verdicts depend on the durability
    manifest (owner coverage, [attrs] agreement), so the per-file cache
    key must incorporate the manifests' content hash — including
    env-overridden manifests the blob-level analyzer hash never sees.
    Pre-fix, run 2 served the stale 'clean' verdict from run 1's cache."""
    work = tmp_path / "pkg"
    work.mkdir()
    (work / "mod.py").write_text(
        "# ballista-lint: path=ballista_tpu/scheduler/mod.py\n"
        "class Thing:\n"
        "    def __init__(self):\n"
        "        self.a = 1\n"
    )
    cache = tmp_path / "cache.json"
    manifest = tmp_path / "durability.toml"
    env = dict(os.environ, BALLISTA_DURABILITY_MANIFEST=str(manifest))

    def run():
        proc = subprocess.run(
            [sys.executable, "-m", "dev.analysis", str(work), "--json",
             "--cache-file", str(cache)],
            cwd=str(REPO), capture_output=True, text=True, env=env,
        )
        return proc.returncode, json.loads(proc.stdout)

    # manifest v1: Thing is nobody's owner -> the unannotated attr is fine
    manifest.write_text("[attrs]\n")
    rc1, out1 = run()
    assert rc1 == 0 and out1["ok"], out1["findings"]
    assert out1["stats"]["cache_hits"] == 0

    # manifest v2 makes Thing an owner: the SAME file (same mtime/size)
    # must be re-analyzed and flag the missing annotation
    manifest.write_text(
        "[[owners]]\n"
        'module = "scheduler.mod"\n'
        'class = "Thing"\n'
        "[attrs]\n"
    )
    rc2, out2 = run()
    assert rc2 == 1 and not out2["ok"], out2
    assert out2["stats"]["cache_hits"] == 0  # stale entry NOT served
    assert any(
        f["rule"] == "durability"
        and "no `# durability:` annotation" in f["message"]
        for f in out2["findings"]
    ), out2["findings"]

    # unchanged manifest: the refreshed verdict is served from cache
    rc3, out3 = run()
    assert rc3 == 1
    assert out3["stats"]["cache_hits"] == out3["stats"]["files"] == 1
    assert out3["findings"] == out2["findings"]


def test_suppression_budget_enforced(tmp_path):
    p = tmp_path / "budget.py"
    lines = ["# ballista-lint: path=ballista_tpu/ops/fixture_budget.py"]
    for i in range(6):
        lines.append(f"x{i} = {i}  # ballista-lint: disable=lint-usage -- r{i}")
    p.write_text("\n".join(lines) + "\n")
    proc = subprocess.run(
        [sys.executable, "-m", "dev.analysis", str(p), "--no-cache", "--json"],
        cwd=str(REPO), capture_output=True, text=True,
    )
    out = json.loads(proc.stdout)
    assert out["over_suppression_budget"] and proc.returncode == 1

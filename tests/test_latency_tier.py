"""Low-latency serving tier (ISSUE 8): push dispatch, the persistent AOT
program cache, streaming result collect, and their satellites.

Four layers, mirroring the subsystem's spread:

- push-dispatch units: pump credit bounds, stale-attempt rejection, the
  per-partition completion notifications on the running job status;
- AOT cache units (ops/aotcache.py): disk roundtrip, corrupted /
  fingerprint-mismatched artifact fallback (reason recorded), the
  `aot.load` chaos site, prewarm;
- end-to-end standalone-cluster runs: push-dispatched queries with ZERO
  poll dispatches, stream drop -> poll fallback -> re-subscribe, a warm
  AOT tier answering with ZERO fresh traces, streaming collect bit-equal
  to buffered, mid-fetch loss routing through ReportLostPartition, and
  seeded `scheduler.push` chaos staying bit-identical to fault-free;
- result-cache eviction (PR 7 residue): size bound LRU-by-last-hit, TTL,
  restart survival of the eviction order.
"""

import logging
import os
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from ballista_tpu.client import BallistaContext
from ballista_tpu.config import BallistaConfig
from ballista_tpu.executor.runtime import StandaloneCluster
from ballista_tpu.ops import aotcache
from ballista_tpu.ops.runtime import (
    recovery_stats,
    serving_stats,
    tenancy_stats,
)
from ballista_tpu.proto import ballista_pb2 as pb
from ballista_tpu.scheduler.kv import MemoryBackend, SqliteBackend
from ballista_tpu.scheduler.server import SchedulerServer, _PushSubscriber
from ballista_tpu.scheduler.state import SchedulerState

logging.getLogger("ballista.executor").setLevel(logging.CRITICAL)


@pytest.fixture()
def tpath(tmp_path):
    """3-file parquet table: multi-partition scans, so plans really have
    a shuffle stage and multiple tasks per stage."""
    d = tmp_path / "t"
    d.mkdir()
    for part in range(3):
        rows = range(part * 200, (part + 1) * 200)
        pq.write_table(
            pa.table(
                {
                    "k": pa.array([i % 7 for i in rows], type=pa.int64()),
                    "v": pa.array([float(i) * 0.5 for i in rows]),
                }
            ),
            str(d / f"part-{part}.parquet"),
        )
    return str(d)


def _wait_for(predicate, timeout=10.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


# ---------------------------------------------------------------------------
# push-dispatch units
# ---------------------------------------------------------------------------


def _server_with_job(tpath, **extra):
    """Synchronous-planning scheduler over a memory store with one planned
    2-stage job and a registered executor — the pump unit-test bed."""
    server = SchedulerServer(
        MemoryBackend(),
        config=BallistaConfig({"ballista.cache.results": "false",
                               "ballista.shuffle.partitions": "4", **extra}),
        synchronous_planning=True,
    )
    server.state.save_executor_metadata(
        pb.ExecutorMetadata(id="e1", host="h", port=1)
    )
    from ballista_tpu.engine import ExecutionContext
    from ballista_tpu.serde.logical import plan_to_proto

    ectx = ExecutionContext()
    ectx.register_parquet("t", tpath)
    plan = ectx.sql("select k, sum(v) as s from t group by k").logical_plan()
    params = pb.ExecuteQueryParams()
    params.logical_plan.CopyFrom(plan_to_proto(plan))
    job_id = server.ExecuteQuery(params).job_id
    return server, job_id


def test_pump_respects_credit_and_frees_on_status(tpath):
    server, job_id = _server_with_job(tpath)
    sub = _PushSubscriber("e1", slots=2)
    with server._push_mu:
        server._subscribers["e1"] = sub
    with server.state.kv.lock():
        n = server._pump_pushes()
    # credit bound: only `slots` pushed even though stage 1 has more tasks
    assert n == 2 and sub.queue.qsize() == 2
    assert len(sub.outstanding) == 2
    with server.state.kv.lock():
        assert server._pump_pushes() == 0  # saturated
    # a terminal status for one pushed task frees its credit (the PollWork
    # resolution path); the next pump refills
    td = sub.queue.get_nowait()
    st = pb.TaskStatus()
    st.partition_id.CopyFrom(td.task_id)
    st.attempt = td.attempt
    st.completed.executor_id = "e1"
    st.completed.path = "/x"
    poll = pb.PollWorkParams(metadata=pb.ExecutorMetadata(id="e1", host="h", port=1))
    poll.task_status.add().CopyFrom(st)
    server.PollWork(poll)
    assert len(sub.outstanding) == 2  # one resolved, one refilled by pump


def test_stale_attempt_push_rejected(tpath):
    """A pushed task requeued behind the executor's back (attempt bumped):
    the executor's late report with the OLD attempt is dropped, and the
    pump's credit re-verification frees the stale entry."""
    server, job_id = _server_with_job(tpath)
    st = server.state
    sub = _PushSubscriber("e1", slots=1)
    with server._push_mu:
        server._subscribers["e1"] = sub
    with st.kv.lock():
        assert server._pump_pushes() == 1
    td = sub.queue.get_nowait()
    pid = td.task_id
    recovery_stats(reset=True)
    # the task is requeued (e.g. orphan reconciliation) -> attempt 1
    with st.kv.lock():
        cur = st.get_task_status(pid.job_id, pid.stage_id, pid.partition_id)
        assert st.requeue_task(cur, "e1", "requeued under test", limit=3)
    # the executor finishes the STALE attempt and reports it
    late = pb.TaskStatus()
    late.partition_id.CopyFrom(pid)
    late.attempt = td.attempt
    late.completed.executor_id = "e1"
    late.completed.path = "/stale"
    with st.kv.lock():
        assert not st.accept_task_status(late)
    assert recovery_stats(reset=True).get("stale_status_dropped") == 1
    # pump re-verification: the stale outstanding entry no longer matches
    # the KV (attempt moved on), so its credit frees and the retry pushes
    with st.kv.lock():
        assert server._pump_pushes() == 1
    refetched = sub.queue.get_nowait()
    assert refetched.attempt == td.attempt + 1


def test_push_chaos_kills_stream_and_leaves_assignment(tpath):
    """rate=1.0 on scheduler.push: the delivery is torn AFTER the Running
    flip — the subscriber dies with it and the task stays Running in the
    ledger (the orphaned-assignment machinery owns recovery from there)."""
    server, job_id = _server_with_job(
        tpath,
        **{"ballista.chaos.rate": "1.0",
           "ballista.chaos.sites": "scheduler.push"},
    )
    sub = _PushSubscriber("e1", slots=2)
    with server._push_mu:
        server._subscribers["e1"] = sub
    recovery_stats(reset=True)
    with server.state.kv.lock():
        assert server._pump_pushes() == 0
    assert sub.closed.is_set()
    # nothing delivered: the queue holds only the close() sentinel
    assert sub.queue.get_nowait() is None and sub.queue.qsize() == 0
    assert recovery_stats(reset=True).get("chaos_push_torn") == 1
    # the assignment stands (Running, in the durable ledger), exactly like
    # a PollWork response lost in transit
    running = [
        t for t in server.state.get_job_tasks(job_id)
        if t.WhichOneof("status") == "running"
    ]
    assert len(running) == 1
    assert len(server.state._assigned) == 1


def test_partial_location_published_per_completed_partition(tpath):
    """synchronize_job_status publishes final-stage completions on the
    RUNNING status (the streaming client's per-partition notification)."""
    server, job_id = _server_with_job(tpath)
    st = server.state
    tasks = st.get_job_tasks(job_id)
    final_stage = max(t.partition_id.stage_id for t in tasks)
    finals = sorted(
        (t for t in tasks if t.partition_id.stage_id == final_stage),
        key=lambda t: t.partition_id.partition_id,
    )
    assert len(finals) >= 2
    done = pb.TaskStatus()
    done.partition_id.CopyFrom(finals[1].partition_id)
    done.completed.executor_id = "e1"
    done.completed.path = "/p1"
    with st.kv.lock():
        st.accept_task_status(done)
        st.synchronize_job_status(job_id)
    js = st.get_job_metadata(job_id)
    assert js.WhichOneof("status") == "running"
    locs = list(js.running.partial_location)
    assert [pl.partition_id.partition_id for pl in locs] == [
        finals[1].partition_id.partition_id
    ]
    assert locs[0].path == "/p1" and locs[0].executor_meta.id == "e1"


# ---------------------------------------------------------------------------
# AOT program-cache units
# ---------------------------------------------------------------------------


class _Owner:
    def __init__(self, key):
        self.aot_key = key


def _wrapped(tmp_path, key="stage-A", chaos=None):
    cfg = {"ballista.tpu.aot_cache": str(tmp_path / "aot")}
    if chaos:
        cfg.update(chaos)
    aotcache.configure(BallistaConfig(cfg))

    import jax.numpy as jnp

    def core(n, cols, aux):
        return jnp.stack(
            [jnp.sum(jnp.where(cols[0] == g, cols[1], 0.0)) for g in range(n)]
        ) + aux[0]

    return aotcache.wrap_step(_Owner(key), "unit", core, static_argnums=(0,))


def _args():
    import jax.numpy as jnp

    return (
        3,
        {0: jnp.asarray(np.arange(16, dtype=np.int32) % 3),
         1: jnp.asarray(np.arange(16, dtype=np.float32))},
        [jnp.asarray(np.float32(1.0))],
    )


def test_aot_roundtrip_disk_hit_and_prewarm(tmp_path):
    aotcache.reset(clear_disk_dir=True)
    step = _wrapped(tmp_path)
    serving_stats(reset=True)
    out1 = np.asarray(step(*_args()))
    s = serving_stats(reset=True)
    assert s.get("compile_trace") == 1 and s.get("aot_saved") == 1
    np.testing.assert_array_equal(out1, np.asarray(step(*_args())))
    assert serving_stats(reset=True).get("compile_hit_memory") == 1
    # cold process: fresh wrapper + empty memory map -> disk hit, same bits
    aotcache.reset()
    step2 = _wrapped(tmp_path)
    out2 = np.asarray(step2(*_args()))
    s = serving_stats(reset=True)
    assert s.get("compile_hit_disk") == 1 and not s.get("compile_trace")
    np.testing.assert_array_equal(out1, out2)
    # prewarm: artifacts compile BEFORE any call; the call is a memory hit
    aotcache.reset()
    n = aotcache.prewarm(
        BallistaConfig({"ballista.tpu.aot_cache": str(tmp_path / "aot")})
    )
    assert n == 1
    s = serving_stats(reset=True)
    assert s.get("compile_prewarmed") == 1
    step3 = _wrapped(tmp_path)
    out3 = np.asarray(step3(*_args()))
    s = serving_stats(reset=True)
    assert s.get("compile_hit_memory") == 1 and not s.get("compile_trace")
    np.testing.assert_array_equal(out1, out3)


def test_warm_compiles_without_execute(tmp_path):
    """ISSUE 19 satellite: ``step.warm(...)`` compiles (and persists) a
    signature WITHOUT running the program — the later real call neither
    traces nor compiles, and a cold process warms straight from the
    persisted artifact."""
    import jax.numpy as jnp

    aotcache.reset(clear_disk_dir=True)
    traces = {"n": 0}

    def core(n, cols, aux):
        traces["n"] += 1
        return jnp.stack(
            [jnp.sum(jnp.where(cols[0] == g, cols[1], 0.0)) for g in range(n)]
        ) + aux[0]

    aotcache.configure(
        BallistaConfig({"ballista.tpu.aot_cache": str(tmp_path / "aot")})
    )
    step = aotcache.wrap_step(
        _Owner("warm-A"), "unit", core, static_argnums=(0,)
    )
    serving_stats(reset=True)
    assert step.warm(*_args()) is True
    s = serving_stats(reset=True)
    assert s.get("compile_warmed") == 1 and s.get("aot_saved") == 1
    assert not s.get("compile_trace")
    warm_traces = traces["n"]
    assert warm_traces >= 1  # the warm itself traced (a compile happened)
    # the real call: memory-map hit + jit executable-cache hit — NO retrace
    out = np.asarray(step(*_args()))
    s = serving_stats(reset=True)
    assert s.get("compile_hit_memory") == 1 and not s.get("compile_trace")
    assert traces["n"] == warm_traces  # compile-without-execute held: the
    # signature was never traced again after the warm
    # a second warm finds the signature already resolvable
    assert step.warm(*_args()) is False
    # cold process: the artifact the warm persisted serves a disk warm
    aotcache.reset()
    step2 = aotcache.wrap_step(
        _Owner("warm-A"), "unit", core, static_argnums=(0,)
    )
    serving_stats(reset=True)
    assert step2.warm(*_args()) is True
    s = serving_stats(reset=True)
    assert s.get("compile_hit_disk") == 1 and not s.get("compile_warmed")
    np.testing.assert_array_equal(out, np.asarray(step2(*_args())))


def test_aot_shape_and_stage_keyed(tmp_path):
    """A different shape bucket or a different stage identity is a
    different program — no false sharing."""
    import jax.numpy as jnp

    aotcache.reset(clear_disk_dir=True)
    step = _wrapped(tmp_path)
    serving_stats(reset=True)
    step(*_args())
    wide = (
        3,
        {0: jnp.asarray(np.arange(32, dtype=np.int32) % 3),
         1: jnp.asarray(np.arange(32, dtype=np.float32))},
        [jnp.asarray(np.float32(1.0))],
    )
    step(*wide)  # new shape bucket -> fresh trace
    other = _wrapped(tmp_path, key="stage-B")
    other(*_args())  # new stage identity -> fresh trace
    s = serving_stats(reset=True)
    assert s.get("compile_trace") == 3 and not s.get("compile_hit_memory")


def test_aot_corrupted_artifact_falls_back(tmp_path):
    aotcache.reset(clear_disk_dir=True)
    step = _wrapped(tmp_path)
    out1 = np.asarray(step(*_args()))
    [entry] = aotcache.manifest_entries(str(tmp_path / "aot"))
    blob_path = aotcache._blob_path(str(tmp_path / "aot"), entry["key"])
    with open(blob_path, "rb") as f:
        payload = f.read()
    header, _, _blob = payload.partition(b"\n")
    with open(blob_path, "wb") as f:
        f.write(header + b"\n" + b"garbage-not-a-program")
    aotcache.reset()
    step2 = _wrapped(tmp_path)
    serving_stats(reset=True)
    out2 = np.asarray(step2(*_args()))
    s = serving_stats(reset=True)
    assert s.get("aot_load_error") == 1  # reason recorded
    assert s.get("compile_trace") == 1  # fell back to a fresh compile
    np.testing.assert_array_equal(out1, out2)


def test_aot_fingerprint_mismatch_falls_back(tmp_path):
    """An artifact written by a different jax/jaxlib/backend is rejected
    by its header before deserialization is even attempted."""
    import json

    aotcache.reset(clear_disk_dir=True)
    step = _wrapped(tmp_path)
    out1 = np.asarray(step(*_args()))
    [entry] = aotcache.manifest_entries(str(tmp_path / "aot"))
    blob_path = aotcache._blob_path(str(tmp_path / "aot"), entry["key"])
    with open(blob_path, "rb") as f:
        _header, _, blob = f.read().partition(b"\n")
    with open(blob_path, "wb") as f:
        f.write(json.dumps(
            {"fingerprint": "v0|jax0.0.0|jaxlib0.0.0|tpu", "name": "unit"}
        ).encode() + b"\n" + blob)
    aotcache.reset()
    step2 = _wrapped(tmp_path)
    serving_stats(reset=True)
    out2 = np.asarray(step2(*_args()))
    s = serving_stats(reset=True)
    assert s.get("aot_load_error") == 1 and s.get("compile_trace") == 1
    np.testing.assert_array_equal(out1, out2)
    # prewarm skips it the same way
    aotcache.reset()
    serving_stats(reset=True)
    assert aotcache.prewarm(
        BallistaConfig({"ballista.tpu.aot_cache": str(tmp_path / "aot")})
    ) == 0
    assert serving_stats(reset=True).get("aot_load_error") == 1


def test_aot_load_chaos_torn(tmp_path):
    """rate=1.0 on aot.load: every disk load is torn deterministically and
    falls back to a fresh compile — results identical, reason recorded."""
    aotcache.reset(clear_disk_dir=True)
    step = _wrapped(tmp_path)
    out1 = np.asarray(step(*_args()))
    aotcache.reset()
    step2 = _wrapped(
        tmp_path,
        chaos={"ballista.chaos.rate": "1.0",
               "ballista.chaos.sites": "aot.load"},
    )
    serving_stats(reset=True)
    out2 = np.asarray(step2(*_args()))
    s = serving_stats(reset=True)
    assert s.get("aot_load_error") == 1 and s.get("compile_trace") == 1
    np.testing.assert_array_equal(out1, out2)


def test_aot_bypasses_without_key_or_dir(tmp_path):
    """No aot_key (stage built outside the dispatcher) or no cache dir:
    the wrapper is a plain jit passthrough — no counters, no files."""
    aotcache.reset(clear_disk_dir=True)
    step = _wrapped(tmp_path, key=None)
    serving_stats(reset=True)
    step(*_args())
    assert serving_stats(reset=True) == {}
    assert aotcache.manifest_entries(str(tmp_path / "aot")) == []


# ---------------------------------------------------------------------------
# end-to-end: standalone cluster
# ---------------------------------------------------------------------------


def test_push_dispatch_e2e_zero_poll(tpath):
    cluster = StandaloneCluster(n_executors=2)
    try:
        ctx = BallistaContext(
            *cluster.scheduler_addr,
            settings={"ballista.cache.results": "false"},
        )
        ctx.register_parquet("t", tpath)
        serving_stats(reset=True)
        q = "select k, sum(v) as s from t group by k order by k"
        first = ctx.sql(q).collect()
        again = ctx.sql(q).collect()
        assert again.equals(first)
        s = serving_stats(reset=True)
        assert s.get("dispatch_push", 0) > 0
        assert s.get("dispatch_poll", 0) == 0, s
        assert s.get("task_pushed") == s.get("dispatch_push")
        ctx.close()
    finally:
        cluster.shutdown()


def test_stream_drop_poll_fallback_then_resubscribe(tpath):
    """Stream loss -> polls pull work (automatic fallback) -> re-subscribe
    resumes push. The scheduler's push gate stands in for a mid-rollout
    scheduler that cannot stream."""
    cluster = StandaloneCluster(n_executors=1)
    try:
        ctx = BallistaContext(
            *cluster.scheduler_addr,
            settings={"ballista.cache.results": "false"},
        )
        ctx.register_parquet("t", tpath)
        q = "select k, count(*) as n from t group by k order by k"
        base = ctx.sql(q).collect()
        ex = cluster.executors[0]
        # kill the stream AND refuse re-subscription
        cluster.scheduler_impl.push_enabled = False
        ex.poll_loop._cancel_push()
        assert _wait_for(lambda: not ex.poll_loop._stream_ok.is_set())
        serving_stats(reset=True)
        out = ctx.sql(q).collect()
        s = serving_stats(reset=True)
        assert out.equals(base)
        assert s.get("dispatch_poll", 0) > 0, s
        assert s.get("dispatch_push", 0) == 0
        # scheduler allows streams again: the executor's subscribe loop
        # reconnects by itself and dispatch returns to push
        cluster.scheduler_impl.push_enabled = True
        assert _wait_for(lambda: ex.poll_loop._stream_ok.is_set())
        serving_stats(reset=True)
        out2 = ctx.sql(q).collect()
        s = serving_stats(reset=True)
        assert out2.equals(base)
        assert s.get("dispatch_push", 0) > 0
        assert s.get("dispatch_poll", 0) == 0, s
        ctx.close()
    finally:
        cluster.shutdown()


def test_idle_poll_backoff_decays_and_snaps_back(tpath):
    """Satellite: with a healthy stream the heartbeat decays toward
    idle_poll_max_s; a stream drop snaps it back to 250ms."""
    cluster = StandaloneCluster(
        n_executors=1,
        config=BallistaConfig({"ballista.executor.idle_poll_max_s": "0.6"}),
    )
    try:
        ex = cluster.executors[0]
        loop = ex.poll_loop
        assert _wait_for(lambda: loop._stream_ok.is_set())
        assert _wait_for(
            lambda: loop._poll_interval > 0.25, timeout=15.0
        ), "interval never decayed"
        with loop._mu:
            assert loop._poll_interval <= 0.6 + 1e-9
        cluster.scheduler_impl.push_enabled = False
        loop._cancel_push()
        assert _wait_for(lambda: not loop._stream_ok.is_set())
        # next loop iteration resets to the 250ms floor
        assert _wait_for(
            lambda: abs(loop._poll_interval - 0.25) < 1e-9, timeout=10.0
        )
    finally:
        cluster.shutdown()


def test_aot_warm_push_query_zero_trace_zero_poll(tmp_path, tpath):
    """The acceptance path: with prewarm on and push dispatch enabled, a
    repeated small query runs with ZERO fresh traces (compile-hit counter)
    and ZERO poll-dispatched tasks (push counter)."""
    from ballista_tpu.ops import kernels

    aot_dir = str(tmp_path / "aot")
    settings = {
        "ballista.executor.backend": "tpu",
        "ballista.cache.results": "false",
        "ballista.tpu.aot_cache": aot_dir,
        "ballista.tpu.layout_cache_dir": str(tmp_path / "layouts"),
    }
    q = "select k, sum(v) as s, count(*) as n from t group by k order by k"

    def clear_stage_caches():
        with kernels._stage_cache_lock:
            kernels._stage_cache.clear()
            kernels._stage_cache_pins.clear()
            kernels._stage_latest.clear()

    aotcache.reset(clear_disk_dir=True)
    clear_stage_caches()
    cluster = StandaloneCluster(n_executors=1, config=BallistaConfig(settings))
    try:
        ctx = BallistaContext(*cluster.scheduler_addr, settings=settings)
        ctx.register_parquet("t", tpath)
        cold = ctx.sql(q).collect()  # traces + persists the programs
        assert serving_stats(reset=True).get("compile_trace", 0) > 0
        warm = ctx.sql(q).collect()
        s = serving_stats(reset=True)
        assert warm.equals(cold)
        assert s.get("compile_trace", 0) == 0, s
        assert s.get("compile_hit_memory", 0) > 0
        assert s.get("dispatch_poll", 0) == 0 and s.get("dispatch_push", 0) > 0
        ctx.close()
    finally:
        cluster.shutdown()
    # a COLD executor with prewarm on: first query, zero trace, zero poll
    aotcache.reset()
    clear_stage_caches()
    cluster = StandaloneCluster(
        n_executors=1,
        config=BallistaConfig({**settings, "ballista.tpu.prewarm": "true"}),
    )
    try:
        prewarmed = serving_stats(reset=True)
        assert prewarmed.get("compile_prewarmed", 0) > 0
        ctx = BallistaContext(*cluster.scheduler_addr, settings=settings)
        ctx.register_parquet("t", tpath)
        first = ctx.sql(q).collect()
        s = serving_stats(reset=True)
        assert first.equals(cold)
        assert s.get("compile_trace", 0) == 0, s
        assert s.get("dispatch_poll", 0) == 0 and s.get("dispatch_push", 0) > 0
        ctx.close()
    finally:
        cluster.shutdown()


def test_streaming_collect_bit_equality(tpath):
    """Streaming collect (and the raw batch generator) deliver bits
    identical to the buffered path — including a multi-partition final
    stage, where batches must assemble in partition order regardless of
    completion order."""
    cluster = StandaloneCluster(n_executors=2)
    try:
        # no global sort: the final stage keeps its shuffle partitioning,
        # so results really stream partition-by-partition
        q = "select k, sum(v) as s, count(*) as n from t group by k"
        buf_ctx = BallistaContext(
            *cluster.scheduler_addr,
            settings={"ballista.cache.results": "false",
                      "ballista.shuffle.partitions": "4"},
        )
        buf_ctx.register_parquet("t", tpath)
        buffered = buf_ctx.sql(q).collect()
        st_ctx = BallistaContext(
            *cluster.scheduler_addr,
            settings={"ballista.cache.results": "false",
                      "ballista.shuffle.partitions": "4",
                      "ballista.client.stream_results": "true"},
        )
        st_ctx.register_parquet("t", tpath)
        streamed = st_ctx.sql(q).collect()
        assert streamed.equals(buffered)
        # raw generator: same rows, same order
        batches = list(
            st_ctx.collect_stream(st_ctx.sql(q).logical_plan())
        )
        tbl = pa.Table.from_batches(
            batches, schema=batches[0].schema
        ).cast(buffered.schema)
        assert tbl.equals(buffered)
        buf_ctx.close()
        st_ctx.close()
    finally:
        cluster.shutdown()


def test_streaming_lost_partition_recovers(tpath):
    """Mid-fetch loss on the streaming path routes through
    ReportLostPartition + re-poll: the job restarts the lost final-stage
    tasks and the stream completes with the recomputed bits. Same death
    harness as the buffered-path test in test_fault_tolerance (total
    executor death + shortened lease so lineage can reschedule)."""
    import ballista_tpu.scheduler.state as state_mod

    cluster = StandaloneCluster(n_executors=2)
    old_lease = state_mod.EXECUTOR_LEASE_SECS
    state_mod.EXECUTOR_LEASE_SECS = 1.0
    cluster.scheduler_impl.lost_task_check_interval = 0.3
    try:
        settings = {"ballista.cache.results": "false",
                    "ballista.client.stream_results": "true"}
        ctx = BallistaContext(*cluster.scheduler_addr, settings=settings)
        ctx.register_parquet("t", tpath)
        q = "select k, sum(v) as s from t group by k order by k"
        plan = ctx.sql(q).logical_plan()
        baseline = ctx.collect(plan)
        # run to completion, then kill an owning executor COMPLETELY so the
        # streaming fetch hits dead locations
        job_id = ctx.submit(plan)
        st = cluster.scheduler_impl.state

        def completed():
            js = st.get_job_metadata(job_id)
            return js is not None and js.WhichOneof("status") == "completed"

        assert _wait_for(completed, timeout=60.0)
        js = st.get_job_metadata(job_id)
        owners = {pl.executor_meta.id
                  for pl in js.completed.partition_location}
        victim = next(
            ex for ex in cluster.executors if ex.id in owners
        )
        victim.stop()
        recovery_stats(reset=True)
        out = ctx._collect_results(job_id, plan.schema(), timeout=120)
        assert out.equals(baseline)
        rec = recovery_stats(reset=True)
        assert rec.get("result_fetch_restarted", 0) >= 1
        assert rec.get("result_partition_restarted", 0) >= 1
        ctx.close()
    finally:
        state_mod.EXECUTOR_LEASE_SECS = old_lease
        cluster.shutdown()


def _chaos_push_run(tpath, rate, seed):
    cluster = StandaloneCluster(
        n_executors=2,
        config=BallistaConfig({
            "ballista.chaos.rate": str(rate),
            "ballista.chaos.seed": str(seed),
            "ballista.chaos.sites": "scheduler.push",
        }),
    )
    try:
        ctx = BallistaContext(
            *cluster.scheduler_addr,
            settings={"ballista.cache.results": "false"},
        )
        ctx.register_parquet("t", tpath)
        out = ctx.collect(
            ctx.sql(
                "select k, sum(v) as s, count(*) as n from t "
                "group by k order by k"
            ).logical_plan(),
            timeout=90,
        )
        ctx.close()
        return out
    finally:
        cluster.shutdown()


@pytest.mark.slow
def test_push_chaos_bit_identical(tpath):
    """Seeded scheduler.push chaos: torn deliveries kill the stream with
    the assignment already written — recovery (orphan-grace requeue +
    re-subscribe + poll fallback) must deliver bits identical to the
    fault-free run. The seed is scanned so the run provably injects."""
    fault_free = _chaos_push_run(tpath, 0.0, 0)
    for seed in range(20):
        recovery_stats(reset=True)
        serving_stats(reset=True)
        out = _chaos_push_run(tpath, 0.4, seed)
        assert out.equals(fault_free), f"seed {seed} diverged"
        rec = recovery_stats(reset=True)
        if rec.get("chaos_push_torn"):
            assert serving_stats(reset=True).get("push_stream_drop", 0) >= 1
            return
    pytest.fail("no seed in range injected a scheduler.push fault")


# ---------------------------------------------------------------------------
# result-cache eviction (PR 7 residue)
# ---------------------------------------------------------------------------


def _completed(path, executor="e1"):
    c = pb.CompletedJob()
    pl = c.partition_location.add()
    pl.path = path
    pl.executor_meta.id = executor
    return c


def _reg(st, executor="e1"):
    st.save_executor_metadata(
        pb.ExecutorMetadata(id=executor, host="h", port=1)
    )


def test_result_cache_eviction_lru_by_last_hit():
    st = SchedulerState(
        MemoryBackend(), "t",
        config=BallistaConfig({"ballista.cache.results.max_entries": "3"}),
    )
    _reg(st)
    tenancy_stats(reset=True)
    for i in range(3):
        assert st.result_cache_put(f"fp{i}", _completed(f"/p{i}"))
        time.sleep(0.01)
    # hit fp0: it becomes the MOST recent; fp1 (never hit, oldest created)
    # is now the LRU victim
    assert st.result_cache_lookup("fp0") is not None
    assert st.result_cache_put("fp3", _completed("/p3"))
    present = [
        i for i in range(4)
        if st.kv.get(st._key("resultcache", f"fp{i}")) is not None
    ]
    assert present == [0, 2, 3], present
    assert tenancy_stats(reset=True).get("cache_evicted") == 1


def test_result_cache_ttl_expiry():
    st = SchedulerState(
        MemoryBackend(), "t",
        config=BallistaConfig({"ballista.cache.results.ttl_s": "0.05"}),
    )
    _reg(st)
    assert st.result_cache_put("fpx", _completed("/x"))
    assert st.result_cache_lookup("fpx") is not None  # fresh: still a hit
    time.sleep(0.1)
    tenancy_stats(reset=True)
    assert st.result_cache_lookup("fpx") is None
    stats = tenancy_stats(reset=True)
    assert stats.get("cache_expired") == 1
    assert st.kv.get(st._key("resultcache", "fpx")) is None


def test_result_cache_eviction_order_survives_restart():
    """last_hit lives in the KV value: a restarted scheduler on the same
    store evicts in the same order the dead one would have."""
    kv = SqliteBackend.temporary()
    st = SchedulerState(
        kv, "t",
        config=BallistaConfig({"ballista.cache.results.max_entries": "2"}),
    )
    _reg(st)
    assert st.result_cache_put("a", _completed("/a"))
    time.sleep(0.01)
    assert st.result_cache_put("b", _completed("/b"))
    time.sleep(0.01)
    assert st.result_cache_lookup("a") is not None  # a outranks b now
    st2 = SchedulerState(
        kv, "t",
        config=BallistaConfig({"ballista.cache.results.max_entries": "2"}),
    )
    assert st2.result_cache_put("c", _completed("/c"))
    present = [
        fp for fp in ("a", "b", "c")
        if kv.get(st2._key("resultcache", fp)) is not None
    ]
    assert present == ["a", "c"], present


def test_result_cache_unbounded_when_disabled():
    st = SchedulerState(
        MemoryBackend(), "t",
        config=BallistaConfig({"ballista.cache.results.max_entries": "0",
                               "ballista.cache.results.ttl_s": "0"}),
    )
    _reg(st)
    for i in range(8):
        assert st.result_cache_put(f"fp{i}", _completed(f"/p{i}"))
    assert all(
        st.kv.get(st._key("resultcache", f"fp{i}")) is not None
        for i in range(8)
    )

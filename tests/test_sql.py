"""SQL frontend unit tests: parser, planner, DDL, edge cases."""

import datetime

import pyarrow as pa
import pytest

from ballista_tpu.engine import ExecutionContext
from ballista_tpu.errors import SqlError
from ballista_tpu.sql.parser import parse_sql, _add_interval


@pytest.fixture
def ctx(sales_table):
    c = ExecutionContext()
    c.register_record_batches("sales", sales_table, n_partitions=2)
    return c


def test_basic_select(ctx):
    out = ctx.sql("select id, amount * 2 as a2 from sales where amount > 20 order by id").collect()
    assert out.column_names == ["id", "a2"]
    assert out.column("a2").to_pylist() == [60.0, 50.0, 70.0, 90.0, 110.0, 130.0]


def test_group_having_order(ctx):
    out = ctx.sql(
        """
        select region, sum(amount) as total, count(*) as n
        from sales group by region having sum(amount) > 50
        order by total desc
        """
    ).collect()
    assert out.column("region").to_pylist() == ["west", "east"]
    assert out.column("total").to_pylist() == [145.0, 120.0]


def test_order_by_ordinal_and_limit(ctx):
    out = ctx.sql("select id, amount from sales order by 2 desc limit 3").collect()
    assert out.column("amount").to_pylist() == [65.0, 55.0, 45.0]


def test_case_when(ctx):
    out = ctx.sql(
        "select id, case when amount > 30 then 'big' else 'small' end as sz "
        "from sales order by id limit 4"
    ).collect()
    assert out.column("sz").to_pylist() == ["small", "small", "small", "small"]


def test_distinct_union(ctx):
    out = ctx.sql(
        "select region from sales where id < 3 "
        "union select region from sales where id >= 8 order by region"
    ).collect()
    assert out.column("region").to_pylist() == ["east", "west"]


def test_in_list_between_like(ctx):
    out = ctx.sql(
        "select id from sales where region in ('east', 'north') "
        "and amount between 5 and 35 and region like '%t%' order by id"
    ).collect()
    assert out.column("id").to_pylist() == [0, 2, 3, 5, 6]


def test_interval_folding():
    d = datetime.date(1994, 1, 1)
    assert _add_interval(d, 12, 0) == datetime.date(1995, 1, 1)
    assert _add_interval(d, 3, 0) == datetime.date(1994, 4, 1)
    assert _add_interval(datetime.date(1994, 1, 31), 1, 0) == datetime.date(1994, 2, 28)
    assert _add_interval(d, 0, 90) == datetime.date(1994, 4, 1)


def test_parse_errors():
    with pytest.raises(SqlError):
        parse_sql("select from")
    with pytest.raises(SqlError):
        parse_sql("select 1 limit 'x'")
    with pytest.raises(SqlError):
        parse_sql("select 'unterminated")
    with pytest.raises(SqlError):
        parse_sql("select 1 ; garbage")


def test_create_external_table(tmp_path, sales_table):
    import pyarrow.csv as pcsv

    p = tmp_path / "sales.csv"
    pcsv.write_csv(sales_table, p)
    ctx = ExecutionContext()
    ctx.sql(
        f"create external table sales stored as csv with header row location '{p}'"
    )
    out = ctx.sql("select count(*) as n from sales").collect()
    assert out.column("n").to_pylist() == [10]


def test_explain(ctx):
    df = ctx.sql("explain select id from sales")
    plan = df.logical_plan()
    from ballista_tpu.logical.plan import Explain

    assert isinstance(plan, Explain)


def test_table_alias_and_self_join(ctx):
    out = ctx.sql(
        """
        select a.id, b.id as other
        from sales a, sales b
        where a.id = b.id and a.id < 2
        order by a.id
        """
    ).collect()
    assert out.column("id").to_pylist() == [0, 1]
    assert out.column("other").to_pylist() == [0, 1]


def test_derived_table(ctx):
    out = ctx.sql(
        """
        select r, t from (
            select region as r, sum(amount) as t from sales group by region
        ) as sub where t > 50 order by t
        """
    ).collect()
    assert out.column("r").to_pylist() == ["east", "west"]


def test_scalar_subquery_uncorrelated(ctx):
    out = ctx.sql(
        "select id from sales where amount > (select avg(amount) from sales) order by id"
    ).collect()
    assert out.column("id").to_pylist() == [6, 7, 8, 9]


def test_count_star_empty_group(ctx):
    out = ctx.sql("select count(*) as n from sales where amount > 1000").collect()
    assert out.column("n").to_pylist() == [0]


def test_uncorrelated_exists():
    import pyarrow as pa

    from ballista_tpu.engine import ExecutionContext

    c = ExecutionContext()
    c.register_record_batches("a", pa.table({"x": pa.array([1, 2, 3])}))
    c.register_record_batches("b", pa.table({"y": pa.array([10])}))
    c.register_record_batches(
        "empty_t", pa.table({"z": pa.array([], type=pa.int64())})
    )
    assert (
        c.sql("select x from a where exists (select y from b) order by x")
        .collect().column("x").to_pylist() == [1, 2, 3]
    )
    assert c.sql("select x from a where exists (select z from empty_t)").collect().num_rows == 0
    assert (
        c.sql("select x from a where not exists (select z from empty_t) order by x")
        .collect().column("x").to_pylist() == [1, 2, 3]
    )
    assert c.sql("select x from a where not exists (select y from b)").collect().num_rows == 0
    # with an inner predicate and combined with other conjuncts
    assert (
        c.sql("select x from a where x > 1 and exists (select y from b where y = 10) order by x")
        .collect().column("x").to_pylist() == [2, 3]
    )


def test_in_list_with_expressions():
    c = ExecutionContext()
    c.register_record_batches(
        "t", pa.table({"x": pa.array([1, 2, 3]), "y": pa.array([2, 9, 9])})
    )
    # row-wise membership: (x,y) rows are (1,2),(2,9),(3,9)
    assert (
        c.sql("select x from t where x in (y, 3) order by x")
        .collect().column("x").to_pylist() == [3]
    )
    assert (
        c.sql("select x from t where x not in (y, 3) order by x")
        .collect().column("x").to_pylist() == [1, 2]
    )
    assert (
        c.sql("select x from t where x in (y + 1, 1) order by x")
        .collect().column("x").to_pylist() == [1]
    )


def test_not_in_null_probe_three_valued():
    """NULL probes yield NULL under IN and NOT IN for BOTH the literal and
    expression member forms (review regression: literal NOT IN kept NULLs)."""
    c = ExecutionContext()
    c.register_record_batches(
        "t", pa.table({"x": pa.array([1, None, 5]), "y": pa.array([8, 8, 8])})
    )
    assert (
        c.sql("select x from t where x not in (1, 2) order by x")
        .collect().column("x").to_pylist() == [5]
    )
    assert (
        c.sql("select x from t where x not in (1, y - 6) order by x")
        .collect().column("x").to_pylist() == [5]
    )
    assert (
        c.sql("select x from t where x in (5, y - 7) order by x")
        .collect().column("x").to_pylist() == [1, 5]
    )


def test_in_list_null_member_three_valued():
    c = ExecutionContext()
    c.register_record_batches("t", pa.table({"x": pa.array([1, None, 5])}))
    # a NULL member makes NOT IN indefinite for every non-matching row
    assert c.sql("select x from t where x not in (1, null)").collect().num_rows == 0
    assert (
        c.sql("select x from t where x in (5, null)")
        .collect().column("x").to_pylist() == [5]
    )


def test_order_by_non_selected_column():
    """Standard SQL: ORDER BY may use input columns/expressions the SELECT
    list dropped — planned as hidden sort columns, sorted, then stripped."""
    import pyarrow as pa

    from ballista_tpu.engine import ExecutionContext
    from ballista_tpu.errors import BallistaError

    c = ExecutionContext()
    t = pa.table({"a": [3, 1, 2], "b": ["x", "z", "y"], "v": [1.0, 2.0, 3.0]})
    c.register_record_batches("tob", t)
    out = c.sql("select b from tob order by a").collect()
    assert out.column("b").to_pylist() == ["z", "y", "x"]
    assert out.schema.names == ["b"]
    out = c.sql("select b from tob order by a + v desc").collect()
    assert out.column("b").to_pylist() == ["y", "x", "z"]
    # aggregate: order by a group key that was not selected
    out = c.sql("select sum(v) as s from tob group by a order by a").collect()
    assert out.column("s").to_pylist() == [2.0, 3.0, 1.0]
    assert out.schema.names == ["s"]
    # DISTINCT keeps the strict rule (hidden columns would change it)
    import pytest as _pytest

    with _pytest.raises(BallistaError, match="not in output"):
        c.sql("select distinct b from tob order by a").collect()

"""Durability analyzer (ISSUE 18): the replica-coherence classification
of scheduler state is machine-checked. The strict gate: the production
scheduler tree is analyzer-clean (every attribute classified, every
durable mutation KV-paired, every derived rebuild reachable from
recover(), budgets respected); the fixture pair exercises every rule
shape; the --json CLI reports per-rule finding counts and wall time."""

import json
import pathlib
import subprocess
import sys

try:  # py3.11+
    import tomllib as _toml
except ImportError:  # pragma: no cover - py3.10 fallback
    import tomli as _toml  # type: ignore

REPO = pathlib.Path(__file__).resolve().parent.parent
FIXTURES = pathlib.Path(__file__).resolve().parent / "fixtures" / "lint"
MANIFEST = REPO / "dev" / "analysis" / "durability.toml"

sys.path.insert(0, str(REPO))

from dev.analysis.core import analyze_file, run_paths  # noqa: E402


def _durability(path):
    return [f for f in analyze_file(str(path)) if f.rule == "durability"]


# -- the strict gate ---------------------------------------------------------

def test_scheduler_tree_is_durability_clean():
    """Acceptance: zero durability findings over the production scheduler
    package — every SchedulerState/server attribute is classified, every
    durable mutation pairs with a KV op, every derived rebuild is
    reachable from recover(), ephemeral counts are within budget, and the
    manifest agrees with the source annotations."""
    findings, _stats = run_paths(
        [str(REPO / "ballista_tpu" / "scheduler")], use_cache=False
    )
    dur = [f for f in findings if f.rule == "durability"]
    assert dur == [], "\n".join(f.format() for f in dur)


def test_manifest_covers_the_state_surface():
    """The reviewed classification table spans the full state surface:
    three owner classes, all three durability classes in use, and at
    least the ~20 attribute families the first sweep classified."""
    with open(MANIFEST, "rb") as f:
        man = _toml.load(f)
    owners = {(o["module"], o["class"]) for o in man["owners"]}
    assert owners == {
        ("scheduler.state", "SchedulerState"),
        ("scheduler.server", "SchedulerServer"),
        ("scheduler.server", "_PushSubscriber"),
    }
    attrs = man["attrs"]
    assert len(attrs) >= 20
    kinds = {row.split("(")[0] for row in attrs.values()}
    assert kinds == {"durable", "derived", "ephemeral"}
    # the attempt-guard policy names the two guards and carries reasons
    ag = man["attempt_guard"]
    assert set(ag["guards"]) == {"accept_task_status", "_spec_attempt_floor"}
    assert all(reason.strip() for reason in ag["reviewed"].values())


# -- fixture pair ------------------------------------------------------------

def test_durability_fixture_pair():
    """All three classes + the attempt-guard rule + the budgeted-ephemeral
    path: every bad shape fires, the canonical shapes are clean."""
    msgs = [f.message for f in _durability(FIXTURES / "durability_bad.py")]
    assert any("no `# durability:` annotation" in m for m in msgs), msgs
    assert any("needs a KV prefix token" in m for m in msgs)
    assert any("needs a reason" in m for m in msgs)
    assert any("needs the rebuild function's name" in m for m in msgs)
    assert any("conflicting durability classification" in m for m in msgs)
    assert any(
        "no KV operation against prefix 'assignments'" in m for m in msgs
    )
    assert any(
        "without consulting the attempt/ledger guard" in m for m in msgs
    )
    assert any("is NOT reachable from" in m for m in msgs)
    assert any("over its budget of 4" in m for m in msgs)
    assert any("dangling" in m for m in msgs)
    good = analyze_file(str(FIXTURES / "durability_good.py"))
    assert good == [], "\n".join(f.format() for f in good)


def test_attempt_guard_ok_annotation_is_load_bearing(tmp_path):
    """Stripping `# attempt-guard-ok:` from the good fixture's replay
    helper makes the attempt-guard finding appear — the annotation is
    what keeps it clean, not a hole in the rule."""
    src = (FIXTURES / "durability_good.py").read_text()
    needle = "    # attempt-guard-ok: replays a status the caller's guard " \
        "already vetted\n"
    assert needle in src
    p = tmp_path / "stripped.py"
    p.write_text(src.replace(needle, ""))
    msgs = [f.message for f in _durability(p)]
    assert any(
        "'replay_status' folds a TaskStatus" in m
        and "without consulting the attempt/ledger guard" in m
        for m in msgs
    ), msgs


# -- per-rule CLI stats (ISSUE 18 satellite) ---------------------------------

def test_json_reports_per_rule_finding_counts_and_wall_time():
    proc = subprocess.run(
        [sys.executable, "-m", "dev.analysis",
         str(FIXTURES / "durability_bad.py"), "--no-cache", "--json"],
        cwd=str(REPO), capture_output=True, text=True,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    out = json.loads(proc.stdout)
    rules = out["stats"]["rules"]
    dur = rules["durability"]
    assert dur["findings"] >= 8
    assert dur["findings"] == sum(
        1 for f in out["findings"] if f["rule"] == "durability"
    )
    assert dur["wall_s"] >= 0
    # every per-file rule billed its wall time, findings or not
    for rule in ("lock-order", "readback-discipline", "tracer-hygiene"):
        assert rule in rules and rules[rule]["wall_s"] >= 0, rules

"""Device-resident Sort+Limit epilogue fusion (ops/stage.py::_run_topk over
the planner's _topk_pushdown annotation): the device must read back exactly
`limit` rows — bit-identical to what the full readback + host sort+limit
would emit — and fall back gracefully whenever it cannot guarantee that
(boundary ties under un-fused tie-breakers, ineligible key kinds, covers
that would blow the padding budget)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from ballista_tpu.config import BallistaConfig
from ballista_tpu.engine import ExecutionContext
from ballista_tpu.ops import kernels
from ballista_tpu.ops.runtime import readback_stats, reset_residency


def _fresh():
    kernels._stage_cache.clear()
    kernels._stage_cache_pins.clear()
    kernels._stage_latest.clear()
    reset_residency()
    readback_stats(reset=True)


def _ctxs(tmp_path, table, name="t"):
    path = str(tmp_path / f"{name}.parquet")
    pq.write_table(table, path)
    out = {}
    for backend in ("tpu", "cpu"):
        ctx = ExecutionContext(
            BallistaConfig({"ballista.executor.backend": backend})
        )
        ctx.register_parquet(name, path)
        out[backend] = ctx
    return out


def _table(n=30_000, n_groups=2500, seed=0):
    rng = np.random.default_rng(seed)
    return pa.table(
        {
            "g": pa.array(rng.integers(0, n_groups, n), type=pa.int64()),
            "v": pa.array(np.round(rng.uniform(-100, 100, n), 2)),
            "q": pa.array(rng.integers(1, 50, n), type=pa.int64()),
        }
    )


def test_fused_topk_reads_back_limit_rows(tmp_path):
    """The headline contract: selection identical to host sort+limit, d2h
    readback shrunk from every group to `limit` rows."""
    _fresh()
    ctxs = _ctxs(tmp_path, _table())
    sql = ("select g, sum(q) s, min(v) mn from t group by g "
           "order by s desc, g limit 11")
    got = ctxs["tpu"].sql(sql).collect()
    rb = readback_stats(reset=True)
    want = ctxs["cpu"].sql(sql).collect()
    assert got.to_pydict() == want.to_pydict()
    assert rb["rows"] == 11, rb  # NOT the ~2500 groups
    assert rb["readbacks"] == 1


def test_fused_topk_ascending_and_float_score(tmp_path):
    """Ascending order and an f32 float-sum score (the taxi shape): the
    selection must equal the host sort over the device's own aggregate
    output — exercised by re-running the same query with fusion disabled."""
    _fresh()
    ctxs = _ctxs(tmp_path, _table(seed=3))
    sql = ("select g, sum(v) rev from t group by g "
           "order by rev limit 9")
    fused = ctxs["tpu"].sql(sql).collect()
    rb = readback_stats(reset=True)
    assert rb["rows"] == 9
    # fusion off (computed sort key defeats the annotation; rev + 0 orders
    # identically): the full device output through the host Sort must pick
    # the same rows
    _fresh()
    unfused = ctxs["tpu"].sql(
        "select g, sum(v) rev from t group by g order by rev + 0 limit 9"
    ).collect()
    rb2 = readback_stats(reset=True)
    fd, ud = fused.to_pydict(), unfused.to_pydict()
    assert fd["g"] == ud["g"]  # identical selection
    # the cover layout regroups the f32 accumulation (one chunk per group),
    # so float sums agree at the documented device tolerance, not bit-level
    np.testing.assert_allclose(fd["rev"], ud["rev"], rtol=1e-4)
    assert rb2["rows"] > 9  # the floor the fusion removes


def test_multi_key_lexicographic(tmp_path):
    """Two fused aggregate sort keys (desc then asc) + trailing group key:
    selection matches the host's lexicographic order exactly."""
    _fresh()
    rng = np.random.default_rng(5)
    n = 20_000
    # coarse sums force many first-key ties so the second key decides
    t = pa.table(
        {
            "g": pa.array(rng.integers(0, 700, n), type=pa.int64()),
            "a": pa.array(rng.integers(0, 3, n), type=pa.int64()),
            "b": pa.array(rng.integers(1, 100, n), type=pa.int64()),
            "v": pa.array(np.round(rng.uniform(0, 10, n), 2)),
        }
    )
    ctxs = _ctxs(tmp_path, t)
    sql = ("select g, sum(a) sa, min(b) mb from t group by g "
           "order by sa desc, mb, g limit 13")
    got = ctxs["tpu"].sql(sql).collect()
    want = ctxs["cpu"].sql(sql).collect()
    assert got.to_pydict() == want.to_pydict()


def test_boundary_tie_falls_back_to_host_order(tmp_path):
    """k-th and (k+1)-th groups TIE on every fused lane while an un-fused
    trailing key (g desc) orders them AGAINST the device's group-index
    tie-break: the epilogue must detect the boundary tie and fall back to
    the full readback so the host decides."""
    _fresh()
    # sums: group i gets sum i // 2 -> every adjacent pair ties
    rows_g, rows_q = [], []
    for g in range(40):
        rows_g.extend([g] * 4)
        score = g // 2
        rows_q.extend([score, 0, 0, 0])
    t = pa.table(
        {
            "g": pa.array(rows_g, type=pa.int64()),
            "q": pa.array(rows_q, type=pa.int64()),
        }
    )
    ctxs = _ctxs(tmp_path, t)
    # limit 3 boundary lands INSIDE a tied pair; 'g desc' prefers the
    # HIGHER group id, the fused iota lane would prefer the lower
    sql = ("select g, sum(q) s from t group by g "
           "order by s desc, g desc limit 3")
    got = ctxs["tpu"].sql(sql).collect()
    want = ctxs["cpu"].sql(sql).collect()
    assert got.to_pydict() == want.to_pydict()
    assert got.to_pydict()["g"] == [39, 38, 37]


def test_ineligible_key_kind_runs_unfused(tmp_path):
    """avg finalizes to a ratio of its state rows — ranking the sum row
    would order by the wrong quantity. The spec must reject it and the
    normal full-readback path must serve the query correctly."""
    _fresh()
    ctxs = _ctxs(tmp_path, _table(seed=7))
    sql = ("select g, avg(q) a from t group by g "
           "order by a desc, g limit 5")
    got = ctxs["tpu"].sql(sql).collect()
    rb = readback_stats(reset=True)
    want = ctxs["cpu"].sql(sql).collect()
    assert got.to_pydict()["g"] == want.to_pydict()["g"]
    np.testing.assert_allclose(got.to_pydict()["a"], want.to_pydict()["a"],
                               rtol=1e-4)
    assert rb["rows"] > 5  # full readback: fusion never engaged


def test_limit_wider_than_groups_runs_unfused(tmp_path):
    """k >= group count: selection cannot exclude anything, fusion stays
    off, results unchanged."""
    _fresh()
    ctxs = _ctxs(tmp_path, _table(n=2000, n_groups=8, seed=9))
    sql = ("select g, sum(q) s from t group by g "
           "order by s desc, g limit 50")
    got = ctxs["tpu"].sql(sql).collect()
    want = ctxs["cpu"].sql(sql).collect()
    assert got.to_pydict() == want.to_pydict()
    assert got.num_rows == 8


def test_topk_cover_declines_on_skew():
    """_topk_cover_L1: a run longer than TOPK_MAX_L1 (or a cover whose
    padding blows past ~4x the real rows) disables fusion for the
    partition — the default chunking must take over."""
    from ballista_tpu.ops.stage import TOPK_MAX_L1, _topk_cover_L1

    rng = np.random.default_rng(11)
    even = rng.integers(0, 64, 100_000).astype(np.int64)
    even.sort()
    assert _topk_cover_L1(even, 64) is not None
    skew = np.zeros(TOPK_MAX_L1 + 1, dtype=np.int64)  # one monster run
    assert _topk_cover_L1(skew, 1) is None
    # pathological padding: 3 tiny groups + one 4097-run -> cover pads
    # 3 * 8192 + 8192 slots for ~4100 rows, past the 4x budget... but under
    # the 1<<22 floor the small absolute size is accepted
    mixed = np.concatenate([np.repeat(np.arange(3), 1), np.full(4097, 3)])
    assert _topk_cover_L1(np.sort(mixed), 4) is not None
    # scaled up past the absolute floor it declines
    big = np.concatenate(
        [np.repeat(np.arange(4000), 1), np.full(TOPK_MAX_L1, 4000)]
    ).astype(np.int64)
    assert _topk_cover_L1(np.sort(big), 4001) is None


def test_exact_float_minmax_epilogue_composes(tmp_path):
    """Bijected float MIN/MAX as fused SORT KEYS: full-mantissa doubles
    rank bit-exactly (no f32 collapse), and the returned extrema are the
    stored values bit-for-bit."""
    _fresh()
    rng = np.random.default_rng(13)
    n = 25_000
    v = rng.uniform(-1e9, 1e9, n) + rng.uniform(0, 1e-6, n)
    v[::173] = -0.0
    t = pa.table(
        {
            "g": pa.array(rng.integers(0, 900, n), type=pa.int64()),
            "v": pa.array(v),
        }
    )
    ctxs = _ctxs(tmp_path, t)
    sql = ("select g, min(v) mn, max(v) mx from t group by g "
           "order by mn, g limit 17")
    got = ctxs["tpu"].sql(sql).collect()
    rb = readback_stats(reset=True)
    want = ctxs["cpu"].sql(sql).collect()
    gd, wd = got.to_pydict(), want.to_pydict()
    assert gd["g"] == wd["g"]
    for c in ("mn", "mx"):
        for a, b in zip(gd[c], wd[c]):
            assert (a == b == 0.0) or (
                np.float64(a).tobytes() == np.float64(b).tobytes()
            ), (c, a, b)
    assert rb["rows"] == 17


def _skewed_table(seed=17, n_small=3000, monster=2049):
    """One monster group makes the one-chunk cover decline (its L1 would
    pad n_groups * L1 past the budget), forcing the in-program fold
    variant."""
    rng = np.random.default_rng(seed)
    g = np.concatenate([np.arange(n_small), np.full(monster, n_small)])
    return pa.table(
        {
            "g": pa.array(g, type=pa.int64()),
            "v": pa.array(rng.uniform(-1e9, 1e9, len(g))
                          + rng.uniform(0, 1e-6, len(g))),
            "q": pa.array(rng.integers(1, 50, len(g)), type=pa.int64()),
        }
    )


def test_skewed_cover_folds_in_program(tmp_path):
    """q10's shape in miniature: the fused epilogue must still read back
    `limit` rows by segment-folding chunk partials to group states on
    device, bit-exact for min/max (incl. the f64-bijected pair fold)."""
    _fresh()
    from ballista_tpu.ops.stage import _topk_cover_L1

    t = _skewed_table()
    codes = t.column("g").to_numpy().astype(np.int64)
    assert _topk_cover_L1(np.sort(codes), 3001) is None  # fold path it is
    ctxs = _ctxs(tmp_path, t)
    sql = ("select g, min(v) mn, max(v) mx, count(*) c from t group by g "
           "order by mn, g limit 15")
    got = ctxs["tpu"].sql(sql).collect()
    rb = readback_stats(reset=True)
    want = ctxs["cpu"].sql(sql).collect()
    gd, wd = got.to_pydict(), want.to_pydict()
    assert gd["g"] == wd["g"] and gd["c"] == wd["c"]
    for c in ("mn", "mx"):
        for a, b in zip(gd[c], wd[c]):
            assert np.float64(a).tobytes() == np.float64(b).tobytes(), (c, a, b)
    assert rb["rows"] == 15, rb


def test_skewed_int_sum_keeps_full_readback(tmp_path):
    """The fold variant sums int32 in-program where the host fold widens
    to int64 — int-exact SUM aggregates must disable it (full readback,
    exact as ever) rather than risk overflow."""
    _fresh()
    t = _skewed_table(seed=19)
    ctxs = _ctxs(tmp_path, t)
    sql = ("select g, sum(q) s from t group by g "
           "order by s desc, g limit 6")
    got = ctxs["tpu"].sql(sql).collect()
    rb = readback_stats(reset=True)
    want = ctxs["cpu"].sql(sql).collect()
    assert got.to_pydict() == want.to_pydict()
    assert rb["rows"] > 6  # fusion declined, not wrong


def test_too_many_key_lanes_runs_unfused(tmp_path):
    """f64-bijected keys spend TWO int32 lanes each; past TOPK_MAX_KEY_LANES
    the spec declines ("unsupported multi-key widths") and the full
    readback serves the query."""
    _fresh()
    rng = np.random.default_rng(23)
    n = 8000
    # small G: with the spec declined the stage runs the UNROLLED core,
    # whose per-group python loop makes XLA compile time scale with
    # G x aggregates — the lane-cap decline itself is G-independent
    cols = {"g": pa.array(rng.integers(0, 24, n), type=pa.int64())}
    for i in range(4):
        cols[f"v{i}"] = pa.array(rng.uniform(-1e9, 1e9, n))
    ctxs = _ctxs(tmp_path, pa.table(cols))
    aggs = ", ".join(f"min(v{i}) m{i}" for i in range(4))
    order = ", ".join(f"m{i}" for i in range(4))  # 4 x f64 = 8 lanes > 6
    sql = (f"select g, {aggs} from t group by g "
           f"order by {order}, g limit 5")
    got = ctxs["tpu"].sql(sql).collect()
    rb = readback_stats(reset=True)
    want = ctxs["cpu"].sql(sql).collect()
    assert got.to_pydict() == want.to_pydict()
    assert rb["rows"] > 5  # spec declined: full readback, still exact

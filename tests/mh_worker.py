"""Worker process for the multi-host SPMD tests: joins a 2-process x
4-device CPU mesh (jax.distributed + Gloo collectives), executes the
planner-emitted SpmdAggregateExec, and reports results + which scan
partitions THIS process read, as one JSON line on stdout."""

import json
import os
import sys


def main() -> None:
    pid = int(sys.argv[1])
    n_proc = int(sys.argv[2])
    port = sys.argv[3]
    data_dir = sys.argv[4]
    query = sys.argv[5]  # "int_keys" | "string_keys"

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4"
    ).strip()
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ.pop("PALLAS_AXON_REMOTE_COMPILE", None)
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        f"127.0.0.1:{port}", num_processes=n_proc, process_id=pid
    )

    import pyarrow as pa

    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.distributed.planner import DistributedPlanner
    from ballista_tpu.engine import ExecutionContext
    from ballista_tpu.logical import col, functions as F
    from ballista_tpu.ops.stage import FusedAggregateStage
    from ballista_tpu.parallel.spmd_stage import SpmdAggregateExec
    from ballista_tpu.physical.plan import TaskContext

    read_partitions = []
    orig = FusedAggregateStage._scan_batches

    def tracking(self, partition, ctx):
        read_partitions.append(partition)
        return orig(self, partition, ctx)

    FusedAggregateStage._scan_batches = tracking

    cfg = BallistaConfig(
        {
            "ballista.executor.backend": "tpu",
            "ballista.tpu.spmd_stages": "true",
            "ballista.tpu.mesh": "data:8",
        }
    )
    ctx = ExecutionContext(cfg)
    ctx.register_parquet("t", data_dir)
    # int_keys: low-cardinality (unrolled program); highcard: the sorted
    # chunked-segment program (hk has thousands of groups); string_keys:
    # collective decline to host
    key = {"int_keys": "k", "highcard": "hk", "string_keys": "s"}[query]
    df = ctx.table("t").aggregate(
        [col(key)],
        [F.sum(col("v")).alias("sv"), F.count(col("v")).alias("c"),
         F.min(col("v")).alias("mn"), F.sum(col("w")).alias("sw")],
    )
    phys = ctx.create_physical_plan(df.logical_plan())
    stages = DistributedPlanner(cfg).plan_query_stages("mh", phys)

    def find(n):
        if isinstance(n, SpmdAggregateExec):
            return n
        for c in n.children():
            r = find(c)
            if r is not None:
                return r
        return None

    spmd = next(s for s in (find(st) for st in stages) if s is not None)
    tctx = TaskContext(config=cfg, work_dir="/tmp", job_id="mh")
    out = pa.Table.from_batches(list(spmd.execute(0, tctx))).sort_by(key)
    print(
        json.dumps(
            {
                "pid": pid,
                "path": spmd.last_path,
                "read_partitions": sorted(set(read_partitions)),
                "result": {
                    k: [
                        round(v, 6) if isinstance(v, float) else v
                        for v in out.column(k).to_pylist()
                    ]
                    for k in out.schema.names
                },
            }
        )
    )


if __name__ == "__main__":
    main()

"""join_indices correctness vs brute-force oracle."""

import numpy as np
import pyarrow as pa
import pytest

from ballista_tpu.physical.joinutil import combined_key_codes, join_indices


def brute_force(left, right, how):
    pairs = []
    for i, l in enumerate(left):
        for j, r in enumerate(right):
            if l is not None and l == r:
                pairs.append((i, j))
    if how == "inner":
        return set(pairs)
    if how == "left":
        matched = {i for i, _ in pairs}
        return set(pairs) | {(i, -1) for i in range(len(left)) if i not in matched}
    if how == "right":
        matched = {j for _, j in pairs}
        return set(pairs) | {(-1, j) for j in range(len(right)) if j not in matched}
    if how == "full":
        ml = {i for i, _ in pairs}
        mr = {j for _, j in pairs}
        return (
            set(pairs)
            | {(i, -1) for i in range(len(left)) if i not in ml}
            | {(-1, j) for j in range(len(right)) if j not in mr}
        )
    raise ValueError(how)


@pytest.mark.parametrize("how", ["inner", "left", "right", "full"])
def test_join_vs_brute_force(how):
    rng = np.random.default_rng(42)
    left = rng.integers(0, 20, size=50).tolist()
    right = rng.integers(0, 20, size=30).tolist()
    lc, rc = combined_key_codes([pa.array(left)], [pa.array(right)])
    li, ri = join_indices(lc, rc, how)
    got = set(zip(li.tolist(), ri.tolist()))
    assert got == brute_force(left, right, how)


def test_join_with_nulls_never_match():
    left = pa.array([1, None, 2])
    right = pa.array([None, 1, 3])
    lc, rc = combined_key_codes([left], [right])
    li, ri = join_indices(lc, rc, "inner")
    assert list(zip(li.tolist(), ri.tolist())) == [(0, 1)]


def test_semi_anti():
    left = pa.array([1, 2, 3, 4])
    right = pa.array([2, 4, 4])
    lc, rc = combined_key_codes([left], [right])
    semi, _ = join_indices(lc, rc, "semi")
    assert semi.tolist() == [1, 3]
    anti, _ = join_indices(lc, rc, "anti")
    assert anti.tolist() == [0, 2]
    # right-side (probe) variants: build=left, probe=right
    semi_r, _ = join_indices(lc, rc, "semi_right")
    assert semi_r.tolist() == [0, 1, 2]
    anti_r, _ = join_indices(lc, rc, "anti_right")
    assert anti_r.tolist() == []


def test_composite_string_keys():
    lk = [pa.array(["a", "b", "a"]), pa.array([1, 1, 2])]
    rk = [pa.array(["a", "a", "c"]), pa.array([2, 9, 1])]
    lc, rc = combined_key_codes(lk, rk)
    li, ri = join_indices(lc, rc, "inner")
    assert list(zip(li.tolist(), ri.tolist())) == [(2, 0)]


def test_duplicate_build_keys_expand():
    left = pa.array([7, 7, 8])
    right = pa.array([7])
    lc, rc = combined_key_codes([left], [right])
    li, ri = join_indices(lc, rc, "inner")
    assert sorted(li.tolist()) == [0, 1]
    assert ri.tolist() == [0, 0]

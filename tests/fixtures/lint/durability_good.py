# ballista-lint: path=ballista_tpu/scheduler/fixture_durability_good.py
"""GOOD: every attribute carries a durability classification; durable
mutations write through to the KV (directly, via the key helper, or
through a same-file helper); the derived index is rebuilt from
recover(); status folds consult the attempt guard or carry a reviewed
annotation; the ephemeral count stays within the default budget."""


class MiniLedger:
    def __init__(self, kv, namespace):
        self.kv = kv  # durability: ephemeral(the backend handle itself, not state)
        self.namespace = namespace  # durability: ephemeral(identity of this replica's keyspace)
        self._assigned = {}  # durability: durable(assignments)
        self._index = None  # durability: derived(_rebuild_index)

    def _key(self, *parts):
        return "/".join(("/ballista", self.namespace) + parts)

    def _ledger_key(self, task_id):
        return self._key("assignments", task_id)

    def _ledger_put(self, task_id, executor_id):
        self.kv.put(self._ledger_key(task_id), executor_id)

    def assign(self, task_id, executor_id):
        # write-through via the same-file helper (closure reachability)
        self._assigned[task_id] = executor_id
        self._ledger_put(task_id, executor_id)

    def unassign(self, task_id):
        # write-through directly against the declared prefix
        self._assigned.pop(task_id, None)
        self.kv.delete(self._key("assignments", task_id))

    def _rebuild_index(self):
        self._index = {}
        for key, executor_id in self.kv.get_prefix(
            self._key("assignments") + "/"
        ):
            self._index.setdefault(executor_id, []).append(key)

    def recover(self):
        # rebuild-from-KV: the prefix scan repopulates the durable map,
        # then warms the derived index
        self._assigned.clear()
        for key, executor_id in self.kv.get_prefix(
            self._key("assignments") + "/"
        ):
            self._assigned[key.rsplit("/", 1)[-1]] = executor_id
        self._rebuild_index()

    def accept_task_status(self, status):
        return status.attempt >= 0

    def fold_status(self, status):
        # consults the attempt/ledger guard before folding
        if self.accept_task_status(status):
            self.save_task_status(status)

    def save_task_status(self, status):
        self.kv.put(self._key("assignments", status.task_id), status.state)

    # attempt-guard-ok: replays a status the caller's guard already vetted
    def replay_status(self, status):
        self.save_task_status(status)

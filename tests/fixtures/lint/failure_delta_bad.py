# ballista-lint: path=ballista_tpu/scheduler/fixture_failure_delta_bad.py
"""BAD (ISSUE 19): advancement chaos naming an unregistered site and
computing a site name — both evade the chaos registry, so a cache.advance
chaos run could not be reproduced (or even enumerated) from chaos.SITES."""


def publish_advanced(chaos, result_key):
    # unregistered site: "cache.fold" was never added to chaos.SITES
    chaos.maybe_fail("cache.fold", f"fp:{result_key[:16]}")


def publish_tiered(chaos, tier, result_key):
    site = f"cache.{tier}"
    # computed site name: the registry cannot see which site this arms
    chaos.maybe_fail(site, f"fp:{result_key[:16]}")

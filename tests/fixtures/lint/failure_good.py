# ballista-lint: path=ballista_tpu/executor/fixture_failure_good.py
"""GOOD: fetch_failed carries the lost location; chaos goes through
registered sites only."""


def report_fetch_failure(status, exc, me):
    status.fetch_failed.error = str(exc)
    status.fetch_failed.executor_id = me
    status.fetch_failed.map_stage_id = exc.stage_id
    status.fetch_failed.map_partition_id = exc.map_partition
    status.fetch_failed.map_executor_id = exc.executor_id
    status.fetch_failed.path = exc.path


def poll(chaos, n):
    chaos.maybe_fail("rpc.call", f"poll/{n}")
    return chaos.should_inject("executor.death", f"me/poll{n}")

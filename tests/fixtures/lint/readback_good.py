# ballista-lint: path=ballista_tpu/ops/fixture_readback_good.py
"""GOOD: both pairing styles — explicit record_readback, and the
runtime.readback helper."""
import jax
import numpy as np

from ballista_tpu.ops.runtime import readback, record_readback


def run_stage(cols):
    program = jax.jit(lambda c: c)
    arr = np.asarray(program(cols))
    record_readback(arr.shape[-1], arr.nbytes)
    return arr


def run_stage_helper(cols):
    program = jax.jit(lambda c: c)
    return readback(program(cols))


def host_only(batch):
    # np.asarray of host data is not a readback
    return np.asarray(batch.column(0))

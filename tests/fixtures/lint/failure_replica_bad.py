# ballista-lint: path=ballista_tpu/scheduler/fixture_failure_replica_bad.py
"""BAD (ISSUE 20): failover chaos naming an unregistered site and computing
a site name — both evade the chaos registry, so a lease-renewal chaos run
could not be reproduced (or even enumerated) from chaos.SITES."""


def renew_round(chaos, generation, renew_seq):
    # unregistered site: "scheduler.renew" was never added to chaos.SITES
    chaos.maybe_fail("scheduler.renew", f"g{generation}/renew{renew_seq}")


def mint_tiered(chaos, kind, generation, lease_seq):
    site = f"{kind}.lease"
    # computed site name: the registry cannot see which site this arms
    chaos.maybe_fail(site, f"g{generation}/lease{lease_seq}")

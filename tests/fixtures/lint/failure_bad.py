# ballista-lint: path=ballista_tpu/executor/fixture_failure_bad.py
"""BAD: anonymous fetch_failed (no lost location), unregistered chaos site,
computed site name, ad-hoc ChaosInjected raise."""

from ballista_tpu.utils.chaos import ChaosInjected


def report_fetch_failure(status, exc):
    # missing map_executor_id + path: the scheduler can't recompute
    status.fetch_failed.error = str(exc)
    status.fetch_failed.executor_id = "me"


def poll(chaos, n):
    chaos.maybe_fail("poll.heartbeat", f"poll/{n}")  # unregistered site
    site = "rpc." + "call"
    if chaos.should_inject(site, "k"):  # computed site evades the registry
        raise ChaosInjected(site, "k")  # ad-hoc raise outside the injector

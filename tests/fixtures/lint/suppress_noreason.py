# ballista-lint: path=ballista_tpu/ops/fixture_suppress_noreason.py
"""A suppression without a reason does not suppress AND is itself flagged."""
import jax
import numpy as np


def run_stage(cols):
    program = jax.jit(lambda c: c)
    # ballista-lint: disable=readback-discipline
    return np.asarray(program(cols))

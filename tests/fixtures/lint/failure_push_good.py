# ballista-lint: path=ballista_tpu/scheduler/fixture_failure_push_good.py
"""GOOD (ISSUE 8): latency-tier chaos goes through the registered literal
sites — push delivery keyed on the rotated push sequence, AOT loads keyed
on the content-derived program key (a plan coordinate, never a path)."""


def push_deliver(chaos, n):
    return chaos.should_inject("scheduler.push", f"push{n}")


def aot_load(chaos, program_key):
    chaos.maybe_fail("aot.load", f"prog:{program_key[:16]}")

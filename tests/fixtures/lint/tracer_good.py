# ballista-lint: path=ballista_tpu/ops/fixture_tracer_good.py
"""GOOD: data-dependent selection via jnp.where; python-level branching
only on static (non-tracer) structure."""
import jax
import jax.numpy as jnp

N_LANES = 4


@jax.jit
def select(x):
    s = jnp.sum(x)
    return jnp.where(s > 0, x, -x)


def build_core(use_abs):
    def core(x):
        if use_abs:  # closure over a static python bool: fine
            x = jnp.abs(x)
        out = []
        for lane in range(N_LANES):  # static unroll: fine
            out.append(x + lane)
        return jnp.stack(out)

    return core


traced = jax.jit(build_core(True))

# ballista-lint: path=ballista_tpu/scheduler/fixture_failure_push_bad.py
"""BAD (ISSUE 8): latency-tier code naming an unregistered push site and
computing the AOT-load site name — both evade the chaos registry."""


def push_deliver(chaos, n):
    # unregistered site: "scheduler.stream" was never added to chaos.SITES
    return chaos.should_inject("scheduler.stream", f"push{n}")


def aot_load(chaos, tier, program_key):
    site = f"{tier}.load"
    # computed site name: the registry cannot see which site this arms
    chaos.maybe_fail(site, f"prog:{program_key[:16]}")

# ballista-lint: path=ballista_tpu/ops/fixture_decline_bad.py
"""BAD: reasonless decline, silent None decline, ad-hoc bail."""


class UnsupportedOnDevice(Exception):
    pass


def lower(col):
    if col is None:
        raise UnsupportedOnDevice()  # no reason
    if not hasattr(col, "dtype"):
        raise RuntimeError("can't lower")  # ad-hoc bail
    return col


def entry(col):
    try:
        return lower(col)
    except UnsupportedOnDevice:
        return None  # silent decline

# ballista-lint: path=ballista_tpu/executor/fixture_failure_exchange_good.py
"""GOOD (ISSUE 16): HBM-resident exchange chaos goes through the registered
literal ``exchange.evict`` site, keyed on the consumed piece's plan
coordinates + the CONSUMING attempt — a retried consumer draws a fresh
verdict, and an evicted entry only sends the reader down the authoritative
piece ladder (bit-identical output, zero task retries)."""


def probe_registry(chaos, stage_id, map_partition, piece, attempt):
    return chaos.should_inject(
        "exchange.evict",
        f"{stage_id}/{map_partition}/piece{piece}@a{attempt}",
    )

# ballista-lint: path=ballista_tpu/scheduler/fixture_failure_batch_good.py
"""GOOD (ISSUE 13): shared-scan batch-formation chaos goes through the
registered literal site, keyed on the generation-rotated per-process
sequence (a torn formation degrades that dispatch to solo; the next
formation draws a fresh deterministic verdict)."""


def form_batch(chaos, generation, seq):
    chaos.maybe_fail("scheduler.batch", f"g{generation}/batch{seq}")

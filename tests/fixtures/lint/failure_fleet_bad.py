# ballista-lint: path=ballista_tpu/executor/fixture_failure_fleet_bad.py
"""BAD (ISSUE 15): storage/fleet chaos naming an unregistered site and
computing a site name — both evade the chaos registry, so a chaos run could
not be reproduced (or even enumerated) from chaos.SITES."""


def publish_pieces(chaos, stage_id, partition, attempt):
    # unregistered site: "shuffle.publish" was never added to chaos.SITES
    chaos.maybe_fail("shuffle.publish", f"w{stage_id}/{partition}@a{attempt}")


def scale_decision(chaos, direction, seq):
    site = f"fleet.{direction}"
    # computed site name: the registry cannot see which site this arms
    return chaos.should_inject(site, f"scale{seq}")

# ballista-lint: path=ballista_tpu/ops/fixture_routing_bad.py
"""BAD: decline-helper calls with no routing observation in scope and no
cold-path annotation — the bench routing block would silently undercount
these host decisions."""

from ballista_tpu.ops.kernels import host_fallback, step_aside


def silent_host_decision(reason):
    return host_fallback(reason)


def silent_ladder_step(reason):
    return step_aside(reason)


def foreign_observe_does_not_count(metrics, reason):
    metrics.observe("latency", 1.0)  # not the cost store's observe
    return host_fallback(reason)

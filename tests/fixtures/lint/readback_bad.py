# ballista-lint: path=ballista_tpu/ops/fixture_readback_bad.py
"""BAD: compiled-program result materialized with no readback accounting."""
import jax
import numpy as np


def run_stage(cols):
    program = jax.jit(lambda c: c)
    out = program(cols)
    return np.asarray(out)  # unrecorded d2h transfer


def run_via_handle(cols, aux):
    from somewhere import _compile_predicate  # noqa

    compiler, run = _compile_predicate(cols, aux)
    return np.asarray(run(cols, aux))  # unrecorded d2h transfer

# ballista-lint: path=ballista_tpu/scheduler/fixture_failure_sched_good.py
"""GOOD (ISSUE 6): scheduler chaos goes through the registered literal
sites — plan-write tears keyed on plan coordinates + attempt, crash keyed
on the generation-rotated accepted-status sequence."""


def plan_write(chaos, stage_id, partition, attempt):
    chaos.maybe_fail("scheduler.plan_write", f"{stage_id}/{partition}@a{attempt}")


def crash_check(chaos, generation, n):
    return chaos.should_inject("scheduler.crash", f"g{generation}/status{n}")

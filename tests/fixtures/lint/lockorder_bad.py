# ballista-lint: path=ballista_tpu/ops/lockorder_bad.py
"""BAD: an undeclared nesting acquired in BOTH orders (a cycle — a
potential deadlock), a raw unwitnessable threading.Lock with no
annotation, and a make_lock literal that lies about its identity."""
import threading

from ballista_tpu.utils.locks import make_lock

_a_lock = make_lock("ops.lockorder_bad._a_lock")
_b_lock = make_lock("ops.lockorder_bad._b_lock")
_a_state = {}  # guarded-by: _a_lock
_b_state = {}  # guarded-by: _b_lock

_raw_lock = threading.Lock()  # raw + unannotated: two findings

_misnamed = make_lock("ops.other_module._misnamed")  # wrong canonical name


def transfer_ab(k, v):
    with _a_lock:
        _a_state[k] = v
        with _b_lock:  # undeclared edge a -> b
            _b_state[k] = v


def transfer_ba(k, v):
    with _b_lock:
        _b_state[k] = v
        with _a_lock:  # undeclared edge b -> a: CYCLE with transfer_ab
            _a_state[k] = v

# ballista-lint: path=ballista_tpu/scheduler/fixture_failure_sched_bad.py
"""BAD (ISSUE 6): scheduler code naming an unregistered planning site and
computing a scheduler site name — both evade the chaos registry."""


def plan_write(chaos, stage_id, attempt):
    # typo'd/unregistered site: never registered in chaos.SITES
    chaos.maybe_fail("scheduler.plan_commit", f"stage{stage_id}@a{attempt}")


def crash_check(chaos, kind, n):
    site = f"scheduler.{kind}"
    # computed site name: the registry (and seeded-run reproducibility
    # audits) cannot see which site this arms
    return chaos.should_inject(site, f"status{n}")

# ballista-lint: path=ballista_tpu/scheduler/fixture_failure_replica_good.py
"""GOOD (ISSUE 20): replica-failover chaos goes through the registered
literal sites. ``scheduler.lease`` (keyed generation/renewal-round) tears a
housekeeping renewal round BEFORE any lease write, so the owned leases
simply lapse one TTL early and a peer adopts; ``kv.lease`` (keyed
generation/mint-sequence) tears a lease mint BEFORE the planning commit it
rides, so the whole batch declines atomically."""


def renew_round(chaos, generation, renew_seq):
    chaos.maybe_fail("scheduler.lease", f"g{generation}/renew{renew_seq}")


def mint_lease(chaos, generation, lease_seq):
    chaos.maybe_fail("kv.lease", f"g{generation}/lease{lease_seq}")

# ballista-lint: path=ballista_tpu/scheduler/fixture_failure_batch_bad.py
"""BAD (ISSUE 13): batching code naming an unregistered grouping site and
computing the site name — both evade the chaos registry."""


def form_batch(chaos, generation, seq):
    # unregistered site: "scheduler.group" was never added to chaos.SITES
    chaos.maybe_fail("scheduler.group", f"g{generation}/batch{seq}")


def form_batch_computed(chaos, tier, seq):
    site = f"{tier}.batch"
    # computed site name: the registry cannot see which site this arms
    chaos.maybe_fail(site, f"batch{seq}")

# ballista-lint: path=ballista_tpu/ops/fixture_routing_good.py
"""GOOD: every decline-helper call is paired with a routing observation
(or carries a reviewed cold-path annotation)."""

from ballista_tpu.ops import costmodel
from ballista_tpu.ops.kernels import host_fallback, step_aside
from ballista_tpu.ops.runtime import (
    record_join_path,
    record_routing,
    record_routing_event,
)


def declined_with_decision(reason):
    record_routing("host", "fixture")
    return host_fallback(reason)


def declined_with_event(reason):
    record_routing_event("fixture.step_aside")
    return step_aside(reason)


def declined_with_join_counter(reason):
    record_join_path("host_fallback", reason)
    return host_fallback(reason)


def declined_with_cost_observation(reason):
    costmodel.observe("fixture.host", 10, 0.1, engine="host")
    return host_fallback(reason)


def compile_time_check(ok):
    if not ok:
        # cold-path: compile-time probe; the consumer records the decision
        return host_fallback("fixture compile probe")
    return ok

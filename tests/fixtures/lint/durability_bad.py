# ballista-lint: path=ballista_tpu/scheduler/fixture_durability_bad.py
"""BAD: an unannotated attribute, a malformed prefix, a reasonless
ephemeral, a non-identifier rebuild, a conflicting reclassification, a
durable mutation with no KV write in scope, a guardless status fold, a
derived rebuild recover() never reaches, a dangling annotation, and an
over-budget ephemeral population."""


class LeakyLedger:
    def __init__(self, kv, namespace):
        self.kv = kv  # durability: ephemeral(backend handle)
        self.namespace = namespace  # durability: ephemeral(keyspace identity)
        self._orphan = 0
        self._assigned = {}  # durability: durable(assignments)
        self._ledger = {}  # durability: durable(bad prefix!)
        self._idx = None  # durability: derived(_rebuild_idx)
        self._view = None  # durability: derived(not an ident!)
        self._tmp = {}  # durability: ephemeral()
        self._hints = {}  # durability: ephemeral(scheduling hints)
        self._stats = {}  # durability: ephemeral(counters)
        self._notes = {}  # durability: ephemeral(advisory notes)
        self._seen = set()  # durability: ephemeral(dedup memory)

    def _key(self, *parts):
        return "/".join(("/ballista", self.namespace) + parts)

    def assign(self, task_id, executor_id):
        # durable mutation with no KV operation in the same scope
        self._assigned[task_id] = executor_id

    def reset(self):
        self._assigned = {}  # durability: ephemeral(cleared on reset)

    def _rebuild_idx(self):
        self._idx = dict(self.kv.get_prefix(self._key("assignments") + "/"))

    def recover(self):
        # never calls _rebuild_idx: the derived index stays cold forever
        for key, executor_id in self.kv.get_prefix(
            self._key("assignments") + "/"
        ):
            self._assigned[key.rsplit("/", 1)[-1]] = executor_id

    def fold_status(self, status):
        # folds an executor-reported status with no attempt guard
        self.save_task_status(status)

    def save_task_status(self, status):
        self.kv.put(self._key("assignments", status.task_id), status.state)


DANGLING_BEFORE = 1
# durability: ephemeral(floating annotation with no assignment)
DANGLING_AFTER = 2

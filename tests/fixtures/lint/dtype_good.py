# ballista-lint: path=ballista_tpu/ops/fixture_dtype_good.py
"""GOOD: narrow before upload; post-readback host widening to f64 is the
documented result dtype and is not a violation."""
import jax
import jax.numpy as jnp
import numpy as np

from ballista_tpu.ops.runtime import readback


def upload_narrow(col):
    return jnp.asarray(col.astype(np.float32))


def host_fold_after_readback(program, cols):
    stacked = readback(program(cols))
    return stacked.astype(np.float64)  # host-side result widening: fine

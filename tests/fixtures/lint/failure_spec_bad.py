# ballista-lint: path=ballista_tpu/scheduler/fixture_failure_spec_bad.py
"""BAD (ISSUE 11): an ad-hoc second-attempt path — the duplicate is minted
(`speculative = True`) and dispatched with NO durable ledger record, so a
scheduler restart forgets it and first-completion-wins bookkeeping never
sees the pair; plus an unregistered straggler chaos site."""


def speculate(self, pb, cur, executor_id):
    dup = pb.TaskStatus()
    dup.partition_id.CopyFrom(cur.partition_id)
    dup.attempt = cur.attempt + 1
    dup.speculative = True
    # no _spec_put / _ledger_put: invisible to restart recovery
    self._dispatch(executor_id, dup)
    return dup


def straggle(chaos, stage_id, partition, attempt):
    # never registered in chaos.SITES
    return chaos.should_inject(
        "task.straggle", f"{stage_id}/{partition}@a{attempt}"
    )

# ballista-lint: path=ballista_tpu/ops/fixture_guarded_bad.py
"""BAD: guarded state touched without its lock; a holds-lock helper called
lock-free."""
import threading

_lock = threading.Lock()
_totals = {"rows": 0}  # guarded-by: _lock


def bump(n):
    _totals["rows"] += n  # no lock


# holds-lock: _lock
def _bump_locked(n):
    _totals["rows"] += n


def bump_via_helper(n):
    _bump_locked(n)  # caller does not hold _lock


class Registry:
    def __init__(self):
        self._mu = threading.Lock()
        self._entries = []  # guarded-by: self._mu

    def add(self, x):
        self._entries.append(x)  # no lock

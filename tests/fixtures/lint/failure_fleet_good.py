# ballista-lint: path=ballista_tpu/executor/fixture_failure_fleet_good.py
"""GOOD (ISSUE 15): disaggregated-shuffle and elastic-fleet chaos goes
through the registered literal sites — the storage publish/read seams keyed
on plan coordinates + attempt (a retried attempt draws fresh), the scale
decision keyed on a per-process evaluation sequence (a torn decision skips
that evaluation; the next draws fresh)."""


def publish_pieces(chaos, stage_id, partition, attempt):
    chaos.maybe_fail("shuffle.store", f"w{stage_id}/{partition}@a{attempt}")


def read_piece(chaos, stage_id, map_partition, piece, attempt):
    return chaos.should_inject(
        "shuffle.store", f"r{stage_id}/{map_partition}/piece{piece}@a{attempt}"
    )


def scale_decision(chaos, seq):
    return chaos.should_inject("fleet.scale", f"scale{seq}")

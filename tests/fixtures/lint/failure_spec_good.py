# ballista-lint: path=ballista_tpu/scheduler/fixture_failure_spec_good.py
"""GOOD (ISSUE 11): speculation discipline — the minted duplicate attempt
is recorded in the durable speculation ledger in the same scope, a
promotion lands in the assignment ledger, and the straggler chaos site is
the registered literal `task.slow`."""


def speculate(self, pb, cur, key3, executor_id):
    dup = pb.TaskStatus()
    dup.partition_id.CopyFrom(cur.partition_id)
    dup.attempt = cur.attempt + 1
    dup.speculative = True
    # the durable record restart recovery + first-completion-wins read
    self._spec_put(key3, executor_id, dup.attempt)
    return dup


def promote(self, pb, t, spec, key3):
    promoted = pb.TaskStatus()
    promoted.partition_id.CopyFrom(t.partition_id)
    promoted.attempt = spec[1]
    promoted.speculative = True
    promoted.running.executor_id = spec[0]
    # a promotion enters the normal assignment ledger
    self._ledger_put(key3, spec[0], spec[1])
    return promoted


def echo(td, flag):
    # echo site: copies a non-literal — exempt by design
    td.speculative = flag
    return td


def straggle(chaos, stage_id, partition, attempt):
    return chaos.should_inject(
        "task.slow", f"{stage_id}/{partition}@a{attempt}"
    )

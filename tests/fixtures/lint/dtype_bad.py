# ballista-lint: path=ballista_tpu/ops/fixture_dtype_bad.py
"""BAD: float64 reaching the device — in-trace widening and an f64 host
array flowing into a device transfer."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def widen_in_trace(x):
    return x.astype(np.float64)  # f64 compute inside the program


def upload_wide(col):
    wide = col.astype(np.float64)
    return jnp.asarray(wide)  # f64 crosses h2d


def upload_created(n):
    return jnp.asarray(np.zeros(n, dtype=np.float64))

# ballista-lint: path=ballista_tpu/ops/lockorder_good.py
"""GOOD: canonical make_lock names, a manifest-declared forward nesting,
a holds-lock helper, the double-checked insert idiom, and a reviewed
(annotated) check-then-act — all clean under the lock-order rule."""
from ballista_tpu.utils.locks import make_lock

_outer_lock = make_lock("ops.lockorder_good._outer_lock")
_inner_lock = make_lock("ops.lockorder_good._inner_lock")
_jobs = {}  # guarded-by: _outer_lock
_stats = {}  # guarded-by: _inner_lock


def record(job, n):
    # declared in lockorder.toml: _outer_lock ranks before _inner_lock
    with _outer_lock:
        _jobs[job] = n
        with _inner_lock:
            _stats["records"] = _stats.get("records", 0) + 1


# holds-lock: _outer_lock
def _drop_locked(job):
    _jobs.pop(job, None)


def drop(job):
    with _outer_lock:
        _drop_locked(job)


def cached(job, build):
    # double-checked insert: the re-read under the SECOND acquisition makes
    # the release window safe — not a check-then-act finding
    with _outer_lock:
        hit = _jobs.get(job)
    if hit is not None:
        return hit
    made = build(job)
    with _outer_lock:
        hit = _jobs.get(job)
        if hit is None:
            _jobs[job] = made
            hit = made
        return hit


def approximate_total(delta):
    with _inner_lock:
        total = _stats.get("total", 0)
    total = _clamp(total + delta)
    # atomicity-ok: best-effort estimate; last writer wins by design
    with _inner_lock:
        _stats["total"] = total


def refresh_total():
    with _inner_lock:
        total = _stats.get("total", 0)
    if total > 1000:
        return
    total = _rewalk()  # fresh reassignment KILLS the stale-read taint
    with _inner_lock:
        _stats["total"] = total


def _clamp(x):
    return max(0, x)


def _rewalk():
    return 0

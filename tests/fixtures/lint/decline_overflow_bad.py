# ballista-lint: path=ballista_tpu/ops/fixture_overflow_bad.py
"""BAD: M:N join tier-overflow decline that vanishes silently — the
reasonless raise and the bare None make the overflow invisible to bench's
join-path counters."""


class UnsupportedOnDevice(Exception):
    pass


TIERS = (1, 4, 16, 64, 256)


def admit(max_mult):
    for tier in TIERS:
        if max_mult <= tier:
            return tier
    raise UnsupportedOnDevice()  # no reason: which shape overflowed?


def join(max_mult):
    try:
        return admit(max_mult)
    except UnsupportedOnDevice:
        return None  # silent decline: counters report nothing

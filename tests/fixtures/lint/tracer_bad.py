# ballista-lint: path=ballista_tpu/ops/fixture_tracer_bad.py
"""BAD: branching on / materializing tracer values inside traced code,
including a helper reached from the decoration site via the call graph."""
import jax
import jax.numpy as jnp


@jax.jit
def branchy(x):
    s = jnp.sum(x)
    if s > 0:  # tracer has no concrete truth value
        return x
    return float(jnp.max(x))  # host materialization at trace time


def helper(v):
    while jnp.any(v > 0):  # reached from traced `wrapped` below
        v = v - 1
    return v


def wrapped(x):
    return helper(x)


traced = jax.jit(wrapped)

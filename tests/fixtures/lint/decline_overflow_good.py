# ballista-lint: path=ballista_tpu/ops/fixture_overflow_good.py
"""GOOD: M:N join tier-overflow decline through the canonical helpers —
the admission returns (None, reason), the reason is recorded for bench's
join-path counters (kind "step_aside" keeps the admission-tier
distinction), and host_fallback logs + counts the decline (the join
leaves the device entirely, so tracing counts a fallback)."""

from ballista_tpu.ops.kernels import host_fallback, join_multiplicity_tier
from ballista_tpu.ops.runtime import record_join_path


def join(max_mult, probe_slots):
    tier, why = join_multiplicity_tier(max_mult, probe_slots)
    if tier is None:
        record_join_path("step_aside", why)
        return host_fallback(why)
    return tier

# ballista-lint: path=ballista_tpu/ops/fixture_decline_good.py
"""GOOD: reasoned declines through the canonical signals (and, since
ISSUE 10, paired with a routing observation so the bench routing block
counts the host decision)."""

from ballista_tpu.ops.kernels import host_fallback
from ballista_tpu.ops.runtime import UnsupportedOnDevice, record_routing


def lower(col):
    if col is None:
        raise UnsupportedOnDevice("null column has no device representation")
    return col


def entry(col):
    try:
        return lower(col)
    except UnsupportedOnDevice as e:
        record_routing("host", "fixture")
        return host_fallback(f"fixture lowering: {e}")

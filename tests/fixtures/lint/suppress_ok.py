# ballista-lint: path=ballista_tpu/ops/fixture_suppress_ok.py
"""A reasoned suppression silences exactly its rule on its line."""
import jax
import numpy as np


def run_stage(cols):
    program = jax.jit(lambda c: c)
    # ballista-lint: disable=readback-discipline -- fixture: transport layer whose caller records
    return np.asarray(program(cols))

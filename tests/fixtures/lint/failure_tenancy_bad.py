# ballista-lint: path=ballista_tpu/scheduler/fixture_failure_tenancy_bad.py
"""BAD (ISSUE 7): tenancy code naming an unregistered cache site and
computing an admission site name — both evade the chaos registry."""


def cache_put(chaos, fingerprint):
    # unregistered site: "cache.write" was never added to chaos.SITES
    chaos.maybe_fail("cache.write", f"fp:{fingerprint[:16]}")


def admit(chaos, decision, n):
    site = f"scheduler.{decision}"
    # computed site name: the registry cannot see which site this arms
    return chaos.should_inject(site, f"admit{n}")

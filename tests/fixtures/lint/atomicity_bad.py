# ballista-lint: path=ballista_tpu/ops/atomicity_bad.py
"""BAD: check-then-act across a lock release — the read-modify-write of
guarded state spans two acquisitions, so a concurrent writer's update in
the release window is silently lost."""
from ballista_tpu.utils.locks import make_lock

_mu = make_lock("ops.atomicity_bad._mu")
_state = {"n": 0}  # guarded-by: _mu


def lost_update(delta):
    with _mu:
        cur = _state["n"]
    cur = cur + delta  # derived from the stale read: taint propagates
    with _mu:
        _state["n"] = cur  # flagged: re-acquired write from a stale read

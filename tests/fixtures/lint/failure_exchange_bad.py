# ballista-lint: path=ballista_tpu/executor/fixture_failure_exchange_bad.py
"""BAD (ISSUE 16): exchange chaos naming an unregistered site and computing
a site name — both evade the chaos registry, so an exchange chaos run could
not be reproduced (or even enumerated) from chaos.SITES."""


def probe_registry(chaos, stage_id, map_partition, piece, attempt):
    # unregistered site: "exchange.drop" was never added to chaos.SITES
    return chaos.should_inject(
        "exchange.drop",
        f"{stage_id}/{map_partition}/piece{piece}@a{attempt}",
    )


def evict_entry(chaos, tier, key):
    site = f"exchange.{tier}"
    # computed site name: the registry cannot see which site this arms
    return chaos.should_inject(site, key)

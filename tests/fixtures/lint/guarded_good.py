# ballista-lint: path=ballista_tpu/ops/fixture_guarded_good.py
"""GOOD: every touch under the lock (or inside a holds-lock helper whose
callers hold it); __init__ registration is exempt."""
from ballista_tpu.utils.locks import make_lock

_lock = make_lock("ops.fixture_guarded_good._lock")
_totals = {"rows": 0}  # guarded-by: _lock


def bump(n):
    with _lock:
        _totals["rows"] += n


# holds-lock: _lock
def _bump_locked(n):
    _totals["rows"] += n


def bump_via_helper(n):
    with _lock:
        _bump_locked(n)


class Registry:
    def __init__(self):
        self._mu = make_lock("ops.fixture_guarded_good._mu")
        self._entries = []  # guarded-by: self._mu

    def add(self, x):
        with self._mu:
            self._entries.append(x)

# ballista-lint: path=ballista_tpu/scheduler/fixture_failure_delta_good.py
"""GOOD (ISSUE 19): result-cache advancement chaos goes through the
registered literal ``cache.advance`` site, keyed on the advanced entry's
result key — the verdict fires BEFORE any KV write, so a torn publish
leaves no partial entry and the query simply declines to a full recompute
(bit-identical by construction)."""


def publish_advanced(chaos, result_key):
    chaos.maybe_fail("cache.advance", f"fp:{result_key[:16]}")

# ballista-lint: path=ballista_tpu/scheduler/fixture_failure_tenancy_good.py
"""GOOD (ISSUE 7): multi-tenant serving chaos goes through the registered
literal sites — result-cache puts keyed on the content-derived fingerprint
(a plan coordinate), admission keyed on the rotated admission sequence."""


def cache_put(chaos, fingerprint):
    chaos.maybe_fail("cache.put", f"fp:{fingerprint[:16]}")


def admit(chaos, n):
    chaos.maybe_fail("scheduler.admit", f"admit{n}")

"""M:N duplicate-key device join (ops/join.py) vs the host join oracle.

The correctness bar for the retired unique-build-key decline: results must
be BIT-identical to physical/joinutil.join_indices — row multiplicity and
stable order within a probe key included — and overflow shapes must decline
with a recorded reason, never produce wrong rows. The end-to-end case runs
a q3-shaped duplicate-build-key query through both backends and asserts the
device path actually engaged (join-path counter says "device", not
"host_fallback")."""

import numpy as np
import pyarrow as pa
import pytest

from ballista_tpu.config import BallistaConfig
from ballista_tpu.engine import ExecutionContext
from ballista_tpu.ops.join import device_join_indices
from ballista_tpu.ops.kernels import (
    JOIN_GATHER_CAP,
    JOIN_MULTIPLICITY_TIERS,
    join_multiplicity_tier,
)
from ballista_tpu.ops.runtime import join_path_stats
from ballista_tpu.physical.joinutil import join_indices


def _assert_matches_oracle(build, probe):
    res = device_join_indices(build, probe)
    assert res is not None, "device path declined a shape inside the tiers"
    build_idx, probe_idx, counts = res
    bi_o, pi_o = join_indices(build, probe, "inner")
    # bit-equality: same matches, same multiplicity, same order
    assert build_idx.tolist() == bi_o.tolist()
    assert probe_idx.tolist() == pi_o.tolist()
    # counts are the per-probe run-lengths (membership-count consumers)
    np.testing.assert_array_equal(
        counts, np.bincount(pi_o, minlength=len(probe))
    )


# -- property tests ----------------------------------------------------------

@pytest.mark.parametrize("k", [1, 2, 7, 33, 200])
def test_random_multiplicities(k):
    """Every build key duplicated a random 1..k times; probes hit, miss,
    and null."""
    rng = np.random.default_rng(100 + k)
    keys = np.arange(40, dtype=np.int64)
    build = np.repeat(keys, rng.integers(1, k + 1, len(keys)))
    rng.shuffle(build)
    probe = rng.integers(-1, 55, 3000).astype(np.int64)
    _assert_matches_oracle(build, probe)


def test_empty_probe_runs():
    """No probe matches anything: zero-width result, device still runs."""
    build = np.array([5, 5, 5, 9], dtype=np.int64)
    probe = np.array([1, 2, 3], dtype=np.int64)
    join_path_stats(reset=True)
    build_idx, probe_idx, counts = device_join_indices(build, probe)
    assert len(build_idx) == len(probe_idx) == 0
    assert counts.tolist() == [0, 0, 0]
    assert join_path_stats(reset=True)["paths"] == {"device": 1}


def test_nulls_on_both_sides():
    """Null keys (-1 codes) never match — not even each other."""
    build = np.array([-1, -1, 3, 3], dtype=np.int64)
    probe = np.array([-1, 3, -1], dtype=np.int64)
    _assert_matches_oracle(build, probe)


def test_all_duplicate_single_key():
    build = np.full(37, 4, dtype=np.int64)
    probe = np.array([4, 4, 5], dtype=np.int64)
    build_idx, probe_idx, counts = device_join_indices(build, probe)
    assert counts.tolist() == [37, 37, 0]
    assert build_idx.tolist() == list(range(37)) * 2
    _assert_matches_oracle(build, probe)


def test_zipf_skewed_build():
    """Zipf-skewed duplicate counts, clipped at the top tier so the shape
    is admissible — the heaviest admissible skew."""
    rng = np.random.default_rng(9)
    counts = np.minimum(rng.zipf(1.4, 97), JOIN_MULTIPLICITY_TIERS[-1])
    build = np.repeat(np.arange(97, dtype=np.int64), counts)
    rng.shuffle(build)
    probe = rng.integers(0, 120, 8000).astype(np.int64)
    _assert_matches_oracle(build, probe)


# -- admission / overflow ----------------------------------------------------

def test_tier_ladder():
    assert join_multiplicity_tier(0, 1024) == (1, None)
    assert join_multiplicity_tier(1, 1024) == (1, None)
    assert join_multiplicity_tier(2, 1024) == (4, None)
    assert join_multiplicity_tier(256, 1024) == (256, None)
    tier, why = join_multiplicity_tier(257, 1024)
    assert tier is None and "multiplicity" in why
    tier, why = join_multiplicity_tier(64, JOIN_GATHER_CAP)
    assert tier is None and "cap" in why
    # width 1 is exempt from the cap: it transfers exactly the
    # one-int32-per-probe plane the pre-M:N kernel always read back
    assert join_multiplicity_tier(1, JOIN_GATHER_CAP * 4) == (1, None)


def test_overflow_declines_with_reason():
    mult = JOIN_MULTIPLICITY_TIERS[-1] + 1
    build = np.full(mult, 1, dtype=np.int64)
    probe = np.array([1, 2], dtype=np.int64)
    join_path_stats(reset=True)
    assert device_join_indices(build, probe) is None
    stats = join_path_stats(reset=True)
    assert stats["paths"] == {"step_aside": 1}
    assert any("exceeds top tier" in r for r in stats["reasons"])


def test_empty_side_declines_with_reason():
    join_path_stats(reset=True)
    assert device_join_indices(
        np.empty(0, np.int64), np.array([1], dtype=np.int64)
    ) is None
    stats = join_path_stats(reset=True)
    assert stats["paths"] == {"host_fallback": 1}
    assert any("empty join side" in r for r in stats["reasons"])


# -- end to end --------------------------------------------------------------

def _q3_shaped_tables():
    """q3 shape: orders (build side, MANY orders per customer) joined to
    customer on a non-unique build key."""
    rng = np.random.default_rng(42)
    n_cust = 300
    customer = pa.table(
        {
            "c_custkey": pa.array(np.arange(n_cust), type=pa.int64()),
            "c_name": pa.array([f"Customer#{i:09d}" for i in range(n_cust)]),
        }
    )
    # Zipf-skewed order counts per customer (some have dozens of orders;
    # +40 custkeys fall outside the customer table and never match),
    # clipped under the top admission tier so the shape stays on device
    per_cust = np.minimum(rng.zipf(1.3, n_cust + 40), 120)
    o_custkey = np.repeat(
        np.arange(n_cust + 40, dtype=np.int64), per_cust
    )
    rng.shuffle(o_custkey)
    n_ord = len(o_custkey)
    orders = pa.table(
        {
            "o_orderkey": pa.array(np.arange(n_ord), type=pa.int64()),
            "o_custkey": pa.array(o_custkey),
            "o_totalprice": pa.array(
                np.round(rng.uniform(1000, 400000, n_ord), 2)
            ),
        }
    )
    return customer, orders


def test_q3_shaped_duplicate_build_key_runs_on_device():
    customer, orders = _q3_shaped_tables()
    sql = (
        "select o_orderkey, c_name, o_totalprice from orders, customer "
        "where o_custkey = c_custkey"
    )
    out = {}
    for backend in ("tpu", "cpu"):
        ctx = ExecutionContext(
            BallistaConfig({"ballista.executor.backend": backend})
        )
        ctx.register_record_batches("customer", customer, n_partitions=1)
        ctx.register_record_batches("orders", orders, n_partitions=1)
        if backend == "tpu":
            join_path_stats(reset=True)
            out[backend] = ctx.sql(sql).collect()
            stats = join_path_stats(reset=True)
            # acceptance: the duplicate-build-key join ran ON DEVICE
            assert stats["paths"].get("device", 0) >= 1, stats
            assert "host_fallback" not in stats["paths"], stats
            assert "step_aside" not in stats["paths"], stats
        else:
            out[backend] = ctx.sql(sql).collect()
    # bit-equality INCLUDING row order (no ORDER BY: output order is the
    # join emission order, probe-major with stable build order per key)
    assert out["tpu"].to_pylist() == out["cpu"].to_pylist()


def test_left_dataframe_join_duplicate_build_matches_host():
    """LEFT joins take the host path on both backends today; duplicate
    build keys must agree exactly (regression guard for the counts-based
    LEFT lowering that q13/q22 membership counting will build on)."""
    customer, orders = _q3_shaped_tables()
    out = {}
    for backend in ("tpu", "cpu"):
        ctx = ExecutionContext(
            BallistaConfig({"ballista.executor.backend": backend})
        )
        ctx.register_record_batches("o", orders, n_partitions=1)
        ctx.register_record_batches("c", customer, n_partitions=1)
        df = ctx.table("o").join(
            ctx.table("c"), ["o_custkey"], ["c_custkey"], how="left"
        )
        out[backend] = df.collect()
    assert out["tpu"].to_pylist() == out["cpu"].to_pylist()

"""Deterministic fault injection (utils/chaos.py) + chaos acceptance runs.

Chaos runs are SEEDED: every injection verdict is a pure function of
(seed, site, key), keys are built from plan coordinates (never job ids,
paths, or wall clock), so the same seed faults the same work every run —
no flake — and the recovery machinery must deliver results BIT-IDENTICAL
to the fault-free run."""

import time

import pyarrow as pa
import pytest

from ballista_tpu.config import BallistaConfig
from ballista_tpu.errors import RpcError
from ballista_tpu.utils.chaos import (
    SITES,
    ChaosInjected,
    ChaosInjector,
    chaos_from_config,
)

# -- injector unit behavior -------------------------------------------------


def test_verdicts_are_deterministic_and_instance_free():
    a = ChaosInjector(seed=7, rate=0.5)
    b = ChaosInjector(seed=7, rate=0.5)
    keys = [f"1/{i}@a0" for i in range(64)]
    va = [a.should_inject("task.execute", k) for k in keys]
    vb = [b.should_inject("task.execute", k) for k in keys]
    assert va == vb
    assert any(va) and not all(va)
    # a different seed draws a different fault pattern
    c = ChaosInjector(seed=8, rate=0.5)
    assert va != [c.should_inject("task.execute", k) for k in keys]


def test_rate_bounds():
    never = ChaosInjector(seed=1, rate=0.0)
    always = ChaosInjector(seed=1, rate=1.0)
    for i in range(32):
        assert not never.should_inject("flight.fetch", str(i))
        assert always.should_inject("flight.fetch", str(i))
    with pytest.raises(ValueError):
        ChaosInjector(seed=1, rate=1.5)


def test_rate_is_approximately_honored():
    inj = ChaosInjector(seed=3, rate=0.3)
    hits = sum(inj.should_inject("kv.put", f"put{i}") for i in range(2000))
    assert 0.25 < hits / 2000 < 0.35


def test_unregistered_sites_are_rejected():
    inj = ChaosInjector(seed=1, rate=1.0)
    with pytest.raises(ValueError, match="unregistered"):
        inj.should_inject("made.up", "k")
    with pytest.raises(ValueError, match="unregistered"):
        ChaosInjector(seed=1, rate=1.0, sites={"task.execute", "nope"})


def test_site_filter_disarms_other_sites():
    inj = ChaosInjector(seed=1, rate=1.0, sites={"kv.put"})
    assert inj.should_inject("kv.put", "k")
    assert not inj.should_inject("task.execute", "k")


def test_maybe_fail_raises_rpc_shaped_error():
    inj = ChaosInjector(seed=1, rate=1.0)
    with pytest.raises(ChaosInjected) as ei:
        inj.maybe_fail("rpc.call", "PollWork/1")
    assert isinstance(ei.value, RpcError)
    assert "rpc.call" in str(ei.value)


def test_chaos_from_config():
    assert chaos_from_config(BallistaConfig()) is None  # rate 0 = disarmed
    cfg = BallistaConfig({
        "ballista.chaos.rate": "0.25",
        "ballista.chaos.seed": "42",
        "ballista.chaos.sites": "task.execute, flight.fetch",
    })
    inj = chaos_from_config(cfg)
    assert inj is not None and inj.seed == 42 and inj.rate == 0.25
    assert inj.sites == frozenset({"task.execute", "flight.fetch"})
    assert set(SITES) >= inj.sites


# -- seeded chaos acceptance runs -------------------------------------------

GROUP_BY_SQL = (
    "select region, sum(amount) as s, count(*) as n from sales "
    "group by region order by region"
)
JOIN_SQL = (
    "select region, sum(amount * bonus) as weighted from sales, regions "
    "where region = name group by region order by region"
)

# pinned: verdicts are a pure function of (seed, site, plan-coordinate key),
# so this seed injects the same faults on every run of these queries
CHAOS_SEED = 11
CHAOS_SETTINGS = {
    "ballista.chaos.rate": "0.10",
    "ballista.chaos.seed": str(CHAOS_SEED),
    "ballista.chaos.sites": "task.execute,flight.fetch",
    "ballista.shuffle.max_task_retries": "5",
    "ballista.shuffle.partitions": "4",
}
CLEAN_SETTINGS = {"ballista.shuffle.partitions": "4"}


def _register(ctx, sales_table):
    ctx.register_record_batches("sales", sales_table, n_partitions=4)
    ctx.register_record_batches(
        "regions",
        pa.table({"name": ["east", "west", "north"], "bonus": [1.0, 2.0, 3.0]}),
    )


def _run_queries(settings, sales_table, n_executors=2, cluster_config=None):
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.executor.runtime import StandaloneCluster

    cluster = StandaloneCluster(
        n_executors=n_executors, config=cluster_config or BallistaConfig()
    )
    try:
        ctx = BallistaContext(*cluster.scheduler_addr, settings=settings)
        _register(ctx, sales_table)
        out = {}
        for name, sql in (("group_by", GROUP_BY_SQL), ("join", JOIN_SQL)):
            out[name] = ctx.sql(sql).collect()
        ctx.close()
        return out
    finally:
        cluster.shutdown()


def test_chaos_run_is_bit_identical_to_fault_free_run(sales_table):
    """ISSUE 5 acceptance: a seeded chaos run (task + fetch faults) of the
    distributed group-by and join queries completes with results
    bit-identical to the fault-free run, and the recovery counters show the
    faults actually fired and were recovered from."""
    from ballista_tpu.ops.runtime import recovery_stats

    clean = _run_queries(CLEAN_SETTINGS, sales_table)
    recovery_stats(reset=True)
    chaotic = _run_queries(CHAOS_SETTINGS, sales_table)
    stats = recovery_stats(reset=True)
    for name in ("group_by", "join"):
        assert chaotic[name].equals(clean[name]), (
            name, chaotic[name].to_pydict(), clean[name].to_pydict(),
        )
    assert stats.get("chaos_injected", 0) > 0, stats
    assert stats.get("task_retry", 0) > 0, stats


def test_chaos_exhaustion_error_lists_every_attempt(sales_table):
    """ISSUE 5 acceptance: rate=1.0 defeats every retry; the job error
    after exhaustion names each attempt (executor + cause)."""
    from ballista_tpu.errors import ExecutionError

    settings = {
        "ballista.chaos.rate": "1.0",
        "ballista.chaos.seed": "1",
        "ballista.chaos.sites": "task.execute",
        "ballista.shuffle.max_task_retries": "1",
        "ballista.shuffle.partitions": "2",
    }
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.executor.runtime import StandaloneCluster

    cluster = StandaloneCluster(n_executors=2)
    try:
        ctx = BallistaContext(*cluster.scheduler_addr, settings=settings)
        _register(ctx, sales_table)
        with pytest.raises(ExecutionError) as ei:
            ctx.sql(GROUP_BY_SQL).collect()
        msg = str(ei.value)
        assert "attempt 0 on " in msg and "attempt 1 on " in msg, msg
        assert "chaos[task.execute]" in msg
        assert "after 2 attempt(s)" in msg
        ctx.close()
    finally:
        cluster.shutdown()


def _find_death_seed():
    """Deterministically scan for a seed where executor local-0 dies within
    its first few polls and local-1 survives the whole run — pure hashing,
    no cluster involved, so the scan result is stable forever."""
    for seed in range(2000):
        inj = ChaosInjector(seed, rate=0.005, sites={"executor.death"})

        def death_poll(eid, horizon):
            for n in range(1, horizon):
                if inj.should_inject("executor.death", f"{eid}/poll{n}"):
                    return n
            return None

        d0 = death_poll("local-0", 17)
        if d0 is not None and 4 <= d0 and death_poll("local-1", 400) is None:
            return seed
    pytest.fail("no death seed found in scan range")


def test_chaos_executor_death_recovers_bit_identical(sales_table):
    """ISSUE 5 acceptance: executor-death + fetch-fault injection in one
    seeded run — one executor chaos-dies mid-job (heartbeat AND data plane),
    the survivor recomputes, results stay bit-identical."""
    import ballista_tpu.scheduler.state as state_mod
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.executor.runtime import StandaloneCluster
    from ballista_tpu.ops.runtime import recovery_stats

    death_seed = _find_death_seed()
    clean = _run_queries(CLEAN_SETTINGS, sales_table)

    cluster_config = BallistaConfig({
        "ballista.chaos.rate": "0.005",
        "ballista.chaos.seed": str(death_seed),
        "ballista.chaos.sites": "executor.death",
    })
    old_lease = state_mod.EXECUTOR_LEASE_SECS
    state_mod.EXECUTOR_LEASE_SECS = 1.0
    recovery_stats(reset=True)
    cluster = StandaloneCluster(n_executors=2, config=cluster_config)
    cluster.scheduler_impl.lost_task_check_interval = 0.3
    try:
        ctx = BallistaContext(*cluster.scheduler_addr, settings=CHAOS_SETTINGS)
        _register(ctx, sales_table)
        out = {}
        for name, sql in (("group_by", GROUP_BY_SQL), ("join", JOIN_SQL)):
            # a job that COMPLETED with final partitions on the executor
            # that chaos-killed right after is restarted through lineage by
            # the fetch-time ReportLostPartition path (ISSUE 6) — no
            # resubmission workaround needed anymore
            out[name] = ctx.sql(sql).collect()
        ctx.close()
        for name in ("group_by", "join"):
            assert out[name].equals(clean[name]), (
                name, out[name].to_pydict(), clean[name].to_pydict(),
            )
        stats = recovery_stats(reset=True)
        assert stats.get("chaos_injected", 0) > 0, stats
        # the dying executor's chaos verdict is deterministic; whether its
        # death interrupts live work depends on scheduling, so only the
        # injection itself is asserted unconditionally
        assert stats.get("chaos_executor_death", 0) >= 1, stats
    finally:
        state_mod.EXECUTOR_LEASE_SECS = old_lease
        cluster.shutdown()

"""Narrow device residency (runtime.narrow_column / widen_cols).

HBM capacity and h2d bandwidth bound SF=100 (SURVEY §7 hard part 4): columns
are stored narrow on device and widened in-program. These tests pin the
contract: widening reproduces the canonical int32/f32 arrays bit-exactly,
choices stay stable across batches (no per-batch retraces), and prepared
stages actually hold narrow arrays (the residency win is real).
"""

import numpy as np
import pyarrow as pa

from ballista_tpu.ops.runtime import (
    _LUT_MAX_VALUES,
    entry_device_bytes,
    narrow_column,
    widen_cols,
)


def _widen_np(cols):
    """Host-side mirror of the in-program widen (avoids needing jax here)."""
    out = widen_cols(cols)
    return {k: np.asarray(v) for k, v in out.items()}


def test_int32_narrows_by_range():
    small = np.array([-5, 0, 100], dtype=np.int32)
    mid = np.array([-30000, 0, 30000], dtype=np.int32)
    big = np.array([0, 1 << 20], dtype=np.int32)
    a, lut, ch = narrow_column(small)
    assert a.dtype == np.int8 and lut is None and ch == "int8"
    b, lut, ch = narrow_column(mid)
    assert b.dtype == np.int16 and lut is None and ch == "int16"
    c, lut, ch = narrow_column(big)
    assert c.dtype == np.int32 and lut is None and ch == "int32"


def test_int_choice_stable_across_batches():
    # batch 2 would fit int8 on its own, but the prior (int16) wins: the
    # jitted step must see ONE dtype per column across batches
    wide_first = np.array([0, 3000], dtype=np.int32)
    _, _, ch = narrow_column(wide_first)
    assert ch == "int16"
    small_second = np.array([1, 2], dtype=np.int32)
    arr, _, ch2 = narrow_column(small_second, prior=ch)
    assert ch2 == "int16" and arr.dtype == np.int16
    # escalation the other way is allowed (one bounded retrace)
    arr3, _, ch3 = narrow_column(wide_first, prior="int8")
    assert ch3 == "int16" and arr3.dtype == np.int16


def test_float32_lut_roundtrip_exact():
    grid = np.round(np.arange(0, 0.11, 0.01), 2).astype(np.float32)  # 11 values
    rng = np.random.default_rng(7)
    col = grid[rng.integers(0, len(grid), 8192)]
    codes, lut, ch = narrow_column(col)
    assert ch == "lut" and codes.dtype == np.uint8
    assert len(lut) == _LUT_MAX_VALUES  # fixed length: stable jit signature
    import jax.numpy as jnp

    wide = _widen_np({0: (jnp.asarray(codes), jnp.asarray(lut))})[0]
    assert wide.dtype == np.float32
    np.testing.assert_array_equal(wide, col)  # bit-exact, not approx


def test_float32_high_cardinality_stays_wide():
    col = np.random.default_rng(0).uniform(0, 1e6, 8192).astype(np.float32)
    a, lut, ch = narrow_column(col)
    assert a.dtype == np.float32 and lut is None and ch == "wide"
    # and a "wide" prior skips the sample/encode probe entirely
    a2, lut2, ch2 = narrow_column(col, prior="wide")
    assert lut2 is None and ch2 == "wide"


def test_float32_small_batches_skip_lut_unless_prior():
    col = np.zeros(128, dtype=np.float32)  # under _LUT_MIN_ROWS
    _, lut, ch = narrow_column(col)
    assert lut is None
    # a remainder batch of a column earlier batches LUT-encoded must keep
    # the (codes, lut) structure — a structure flip would retrace the step
    _, lut2, ch2 = narrow_column(col, prior="lut")
    assert lut2 is not None and ch2 == "lut"


def test_widen_cols_int_roundtrip():
    import jax.numpy as jnp

    src = np.array([-7, 0, 90], dtype=np.int32)
    narrow, _, _ = narrow_column(src)
    wide = _widen_np({3: jnp.asarray(narrow)})[3]
    assert wide.dtype == np.int32
    np.testing.assert_array_equal(wide, src)
    # bools and wide arrays pass through untouched
    b = jnp.asarray(np.array([True, False]))
    assert _widen_np({0: b})[0].dtype == np.bool_


def test_prepared_stage_holds_narrow_arrays(tmp_path):
    """End-to-end: a fused aggregation over a q1-shaped table keeps narrow
    residency on device and still matches the host backend exactly."""
    import pyarrow.parquet as pq

    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.engine import ExecutionContext

    rng = np.random.default_rng(11)
    n = 20_000
    table = pa.table(
        {
            "flag": pa.array(rng.choice(["A", "N", "R"], n)),
            "qty": pa.array(rng.integers(1, 51, n).astype(np.int64)),
            "price": pa.array(
                (rng.integers(900, 10_000, n) / 100.0).astype(np.float64)
            ),
            "disc": pa.array(
                np.round(rng.integers(0, 11, n) / 100.0, 2).astype(np.float64)
            ),
        }
    )
    pq.write_table(table, tmp_path / "t.parquet")
    sql = (
        "select flag, sum(qty) as sq, sum(price * (1 - disc)) as rev, "
        "count(*) as c from t group by flag order by flag"
    )
    results = {}
    stages = {}
    for backend in ("tpu", "cpu"):
        ctx = ExecutionContext(
            BallistaConfig({"ballista.executor.backend": backend})
        )
        ctx.register_parquet("t", str(tmp_path))
        results[backend] = ctx.sql(sql).collect().to_pydict()
        if backend == "tpu":
            from ballista_tpu.ops import kernels

            with kernels._stage_cache_lock:
                stages = {
                    k: v for k, v in kernels._stage_cache.items() if v
                }
    assert results["tpu"]["flag"] == results["cpu"]["flag"]
    assert results["tpu"]["sq"] == results["cpu"]["sq"]
    np.testing.assert_allclose(
        results["tpu"]["rev"], results["cpu"]["rev"], rtol=1e-6
    )
    # at least one cached stage holds a narrowed device column: qty (int8)
    # or disc (uint8 LUT codes)
    def narrow_kinds(stage):
        found = []
        for ent in getattr(stage, "_device_cache", {}).values():
            entries = ent["entries"] if ent.get("kind") == "batches" else [ent]
            for e in entries:
                for v in e.get("cols", {}).values():
                    if isinstance(v, tuple):
                        found.append("lut")
                    elif v.dtype.itemsize < 4 and str(v.dtype) != "bool":
                        found.append(str(v.dtype))
        return found

    narrowed = [f for s in stages.values() for f in narrow_kinds(s)]
    assert narrowed, "expected at least one narrow device column"
    assert "lut" in narrowed or "int8" in narrowed


def test_entry_device_bytes_counts_lut_tuples():
    import jax.numpy as jnp

    entry = {
        "cols": {
            0: (jnp.zeros(1024, dtype=jnp.uint8), jnp.zeros(16, dtype=jnp.float32)),
            1: jnp.zeros(1024, dtype=jnp.int16),
        }
    }
    assert entry_device_bytes(entry) == 1024 + 64 + 2048

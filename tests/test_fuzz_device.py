"""Seeded randomized differential testing: random aggregation (and
aggregate-over-join) queries run on BOTH backends and must agree.

The q2 regression (f32 device MIN feeding an equality join) was caught by
a broad differential sweep, not by the targeted suites — this keeps a
deterministic slice of that sweep in CI. Ints compare exactly; floats at
the documented f32 device tolerance."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from ballista_tpu.config import BallistaConfig
from ballista_tpu.engine import ExecutionContext
from ballista_tpu.ops import kernels


def _fresh():
    from ballista_tpu.ops.runtime import reset_residency

    kernels._stage_cache.clear()
    kernels._stage_cache_pins.clear()
    kernels._stage_latest.clear()
    reset_residency()


def _random_table(rng, n):
    cols = {
        "i8": pa.array(rng.integers(-100, 100, n), type=pa.int64()),
        "ibig": pa.array(rng.integers(-10**8, 10**8, n), type=pa.int64()),
        "f": pa.array(np.round(rng.uniform(-1000, 1000, n), 2)),
        "g": pa.array(rng.integers(0, rng.integers(2, 3000), n),
                      type=pa.int64()),
        "s": pa.array([f"tag{v}" for v in rng.integers(0, 9, n)]),
        "d": pa.array(rng.integers(8000, 12000, n), type=pa.int32()).cast(
            pa.date32()
        ),
    }
    return pa.table(cols)


_AGGS = [
    "sum(i8)", "sum(ibig)", "sum(f)", "count(*)", "count(f)",
    "min(i8)", "max(ibig)", "min(d)", "max(d)", "avg(f)", "avg(i8)",
    "sum(f * (1 - 0.1))", "sum(case when i8 > 0 then f else 0 end)",
]
_PREDS = [
    "i8 > 0", "f < 250.5", "s <> 'tag3'", "s in ('tag1', 'tag2', 'tag7')",
    "d >= date '1995-01-01'", "i8 between -50 and 50",
    "s like 'tag%'", "i8 > 0 and f < 0", "i8 < -90 or f > 900",
]


def _random_query(rng):
    keys = list(rng.choice(["g", "s", "d"], size=rng.integers(0, 3),
                           replace=False))
    n_aggs = rng.integers(1, 5)
    aggs = [
        f"{a} as a{i}"
        for i, a in enumerate(rng.choice(_AGGS, size=n_aggs, replace=False))
    ]
    sel = ", ".join(keys + aggs)
    sql = f"select {sel} from t"
    if rng.random() < 0.7:
        sql += f" where {rng.choice(_PREDS)}"
    if keys:
        sql += " group by " + ", ".join(keys)
        sql += " order by " + ", ".join(keys)
    return sql


def _compare(t, c, sql):
    assert t.num_rows == c.num_rows, sql
    assert t.schema.names == c.schema.names, sql
    for name in t.schema.names:
        a, b = t.column(name).to_pylist(), c.column(name).to_pylist()
        if a and isinstance(
            next((x for x in a if x is not None), None), float
        ):
            an = np.array([np.nan if x is None else x for x in a], dtype=float)
            bn = np.array([np.nan if x is None else x for x in b], dtype=float)
            np.testing.assert_allclose(
                an, bn, rtol=1e-3, atol=1e-3, equal_nan=True,
                err_msg=f"{sql} :: {name}",
            )
        else:
            assert a == b, f"{sql} :: {name}"


@pytest.mark.parametrize("seed", range(12))
def test_fuzz_aggregates(tmp_path, seed):
    rng = np.random.default_rng(1000 + seed)
    _fresh()
    table = _random_table(rng, int(rng.integers(1_000, 40_000)))
    path = str(tmp_path / "t.parquet")
    pq.write_table(table, path)
    ctxs = {}
    for backend in ("tpu", "cpu"):
        ctx = ExecutionContext(
            BallistaConfig({"ballista.executor.backend": backend})
        )
        ctx.register_parquet("t", path)
        ctxs[backend] = ctx
    for _ in range(4):
        sql = _random_query(rng)
        _compare(ctxs["tpu"].sql(sql).collect(),
                 ctxs["cpu"].sql(sql).collect(), sql)


@pytest.mark.parametrize("seed", range(8))
def test_fuzz_aggregate_over_join(tmp_path, seed):
    """Random star joins through the factagg/mapped admission machinery."""
    rng = np.random.default_rng(2000 + seed)
    _fresh()
    nk = int(rng.integers(50, 2000))
    nf = int(rng.integers(2_000, 30_000))
    missing = int(rng.integers(0, nk // 4 + 1))
    fact = pa.table(
        {
            "fk": pa.array(rng.integers(0, nk + missing, nf),
                           type=pa.int64()),
            "v": pa.array(np.round(rng.uniform(0, 500, nf), 2)),
            "q": pa.array(rng.integers(1, 50, nf), type=pa.int64()),
            "m": pa.array([f"m{x}" for x in rng.integers(0, 6, nf)]),
        }
    )
    dim = pa.table(
        {
            "dk": pa.array(np.arange(nk), type=pa.int64()),
            "attr": pa.array([f"g{i % rng.integers(2, 40)}"
                              for i in range(nk)]),
            "w": pa.array(rng.integers(0, 10, nk), type=pa.int64()),
        }
    )
    pq.write_table(fact, str(tmp_path / "fact.parquet"))
    pq.write_table(dim, str(tmp_path / "dim.parquet"))
    ctxs = {}
    for backend in ("tpu", "cpu"):
        ctx = ExecutionContext(
            BallistaConfig({"ballista.executor.backend": backend})
        )
        ctx.register_parquet("fact", str(tmp_path / "fact.parquet"))
        ctx.register_parquet("dim", str(tmp_path / "dim.parquet"))
        ctxs[backend] = ctx

    group = rng.choice(["fk", "attr", "m", "fk, attr", "attr, m"])
    aggs = rng.choice(
        ["sum(v)", "count(*)", "sum(q)", "avg(v)", "sum(v * q)",
         "sum(case when attr <> 'g1' then v else 0 end)", "sum(w)",
         "min(q)", "max(q)"],
        size=rng.integers(1, 4), replace=False,
    )
    sel = ", ".join([group] + [f"{a} as a{i}" for i, a in enumerate(aggs)])
    sql = f"select {sel} from dim, fact where dk = fk"
    if rng.random() < 0.6:
        sql += " and " + str(rng.choice(
            ["v > 100", "q < 25", "m <> 'm3'", "w > 2"]
        ))
    sql += f" group by {group} order by {group}"
    _compare(ctxs["tpu"].sql(sql).collect(),
             ctxs["cpu"].sql(sql).collect(), sql)

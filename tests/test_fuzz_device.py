"""Seeded randomized differential testing: random aggregation (and
aggregate-over-join) queries run on BOTH backends and must agree.

The q2 regression (f32 device MIN feeding an equality join) was caught by
a broad differential sweep, not by the targeted suites — this keeps a
deterministic slice of that sweep in CI. Ints compare exactly; floats at
the documented f32 device tolerance."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from ballista_tpu.config import BallistaConfig
from ballista_tpu.engine import ExecutionContext
from ballista_tpu.ops import kernels


def _fresh():
    from ballista_tpu.ops.runtime import reset_residency

    kernels._stage_cache.clear()
    kernels._stage_cache_pins.clear()
    kernels._stage_latest.clear()
    reset_residency()


def _extrema_floats(rng, n):
    """Adversarial float-extrema column: negative-heavy full-mantissa
    doubles with ±0, subnormals, and (on some seeds) NaN — the NaN tables
    must DECLINE the device min/max path (Arrow's host min/max skips NaN)
    and still agree across backends."""
    v = rng.uniform(-1e9, 1e3, n) + rng.uniform(0, 1e-6, n)
    v[rng.integers(0, n, max(1, n // 500))] = -0.0
    v[rng.integers(0, n, max(1, n // 500))] = 0.0
    v[rng.integers(0, n, max(1, n // 700))] = 5e-324  # subnormal
    v[rng.integers(0, n, max(1, n // 700))] = -5e-324
    if rng.random() < 0.4:
        v[rng.integers(0, n, max(1, n // 1000))] = np.nan
    return v


def _null_heavy_strings(rng, n):
    """~45% null string column (its own rng stream, like fx): nulls ride the
    device as -1 dictionary codes and every code predicate must apply SQL
    three-valued logic to them (ops/runtime.py::column_to_numpy)."""
    vals = rng.integers(0, 7, n)
    nulls = rng.random(n) < 0.45
    return pa.array(
        [None if isnull else f"x{v}" for v, isnull in zip(vals, nulls)],
        type=pa.string(),
    )


def _random_table(rng, n):
    cols = {
        "i8": pa.array(rng.integers(-100, 100, n), type=pa.int64()),
        "ibig": pa.array(rng.integers(-10**8, 10**8, n), type=pa.int64()),
        "f": pa.array(np.round(rng.uniform(-1000, 1000, n), 2)),
        # fx and sn draw from their own rngs so the baseline columns (and
        # every query the original stream generates) stay byte-identical
        "fx": pa.array(_extrema_floats(np.random.default_rng(n ^ 0xF10A7), n)),
        "sn": _null_heavy_strings(np.random.default_rng(n ^ 0x5EED), n),
        "g": pa.array(rng.integers(0, rng.integers(2, 3000), n),
                      type=pa.int64()),
        "s": pa.array([f"tag{v}" for v in rng.integers(0, 9, n)]),
        "d": pa.array(rng.integers(8000, 12000, n), type=pa.int32()).cast(
            pa.date32()
        ),
    }
    return pa.table(cols)


# exact aggregates (the True flags) are bit-identical across backends —
# ints stay int32/int64 end to end, float MIN/MAX travels the
# order-preserving bijection — so they may RANK an ORDER BY ... LIMIT
# epilogue (a tolerance-only aggregate ranking the boundary could select
# different rows per backend and that would be a false alarm, not a bug)
_AGGS = [
    ("sum(i8)", True), ("sum(ibig)", True), ("sum(f)", False),
    ("count(*)", True), ("count(f)", True),
    ("min(i8)", True), ("max(ibig)", True), ("min(d)", True),
    ("max(d)", True), ("avg(f)", False), ("avg(i8)", False),
    ("sum(f * (1 - 0.1))", False),
    ("sum(case when i8 > 0 then f else 0 end)", False),
    ("min(f)", True), ("max(f)", True), ("min(fx)", True),
    ("max(fx)", True),
]
# the original generator draws from this prefix of _AGGS (keeping the
# baseline rng stream byte-identical: compile-heavy query shapes stay the
# ones the suite always had); the float-extrema tail joins via the
# epilogue generator's own stream
_N_BASE_AGGS = 13
_PREDS = [
    "i8 > 0", "f < 250.5", "s <> 'tag3'", "s in ('tag1', 'tag2', 'tag7')",
    "d >= date '1995-01-01'", "i8 between -50 and 50",
    "s like 'tag%'", "i8 > 0 and f < 0", "i8 < -90 or f > 900",
]
# null-heavy string predicates (ROADMAP fuzzer slice): selected by their
# OWN rng stream so the baseline queries stay byte-identical. Every shape
# exercises SQL three-valued logic over the -1 null code on device: the
# WHERE collapse must drop NULL rows for =/<>/LIKE/IN, and IS [NOT] NULL
# is the explicit code test.
_NULLSTR_PREDS = [
    "sn is null", "sn is not null", "sn = 'x1'", "sn <> 'x2'",
    "sn like 'x%'", "sn in ('x1', 'x3', 'x5')",
    "sn is null or sn = 'x2'", "sn is not null and sn <> 'x4'",
]


def _random_query(rng, erng, nrng=None):
    """Base query from `rng` (UNCHANGED baseline stream), ORDER BY + LIMIT
    epilogue decisions from the separate `erng`, null-string predicate
    injection from `nrng` — so the base workload stays identical to the
    seed suite's."""
    keys = list(rng.choice(["g", "s", "d"], size=rng.integers(0, 3),
                           replace=False))
    n_aggs = rng.integers(1, 5)
    picks = list(rng.choice(_N_BASE_AGGS, size=n_aggs, replace=False))
    epilogue = erng.random() < 0.5
    if epilogue and erng.random() < 0.5:
        # swap one pick for a float-extrema min/max — only on epilogue
        # queries, which the annotation routes through the vectorized
        # sorted core (no fresh unrolled-core compiles beyond baseline's)
        picks[int(erng.integers(0, len(picks)))] = int(
            erng.integers(_N_BASE_AGGS, len(_AGGS))
        )
    aggs = [f"{_AGGS[p][0]} as a{i}" for i, p in enumerate(picks)]
    sel = ", ".join(keys + aggs)
    sql = f"select {sel} from t"
    if rng.random() < 0.7:
        sql += f" where {rng.choice(_PREDS)}"
    if nrng is not None and nrng.random() < 0.5:
        p = str(nrng.choice(_NULLSTR_PREDS))
        conj = "and" if nrng.random() < 0.7 else "or"
        sql += f" {conj} ({p})" if " where " in sql else f" where ({p})"
    if not keys:
        return sql
    sql += " group by " + ", ".join(keys)
    exact = [f"a{i}" for i, p in enumerate(picks) if _AGGS[p][1]]
    if exact and epilogue:
        # ORDER BY ... LIMIT epilogue over exact ranking keys, ties
        # included (counts/coarse sums collide constantly at these group
        # cardinalities). The trailing group keys make the order total, so
        # a fused device top-k must either match the host selection or
        # detect the boundary tie and fall back — either way bit-equal.
        ranks = [
            f"{a}{' desc' if erng.random() < 0.5 else ''}"
            for a in erng.choice(exact, size=erng.integers(1, len(exact) + 1),
                                 replace=False)
        ]
        sql += " order by " + ", ".join(ranks + keys)
        sql += f" limit {erng.integers(1, 60)}"
    else:
        sql += " order by " + ", ".join(keys)
    return sql


def _compare(t, c, sql):
    assert t.num_rows == c.num_rows, sql
    assert t.schema.names == c.schema.names, sql
    for name in t.schema.names:
        a, b = t.column(name).to_pylist(), c.column(name).to_pylist()
        if a and isinstance(
            next((x for x in a if x is not None), None), float
        ):
            an = np.array([np.nan if x is None else x for x in a], dtype=float)
            bn = np.array([np.nan if x is None else x for x in b], dtype=float)
            np.testing.assert_allclose(
                an, bn, rtol=1e-3, atol=1e-3, equal_nan=True,
                err_msg=f"{sql} :: {name}",
            )
        else:
            assert a == b, f"{sql} :: {name}"


@pytest.mark.parametrize("seed", range(12))
def test_fuzz_aggregates(tmp_path, seed):
    rng = np.random.default_rng(1000 + seed)
    _fresh()
    table = _random_table(rng, int(rng.integers(1_000, 40_000)))
    path = str(tmp_path / "t.parquet")
    pq.write_table(table, path)
    ctxs = {}
    for backend in ("tpu", "cpu"):
        ctx = ExecutionContext(
            BallistaConfig({"ballista.executor.backend": backend})
        )
        ctx.register_parquet("t", path)
        ctxs[backend] = ctx
    erng = np.random.default_rng(5000 + seed)
    nrng = np.random.default_rng(9000 + seed)
    for _ in range(4):
        sql = _random_query(rng, erng, nrng)
        _compare(ctxs["tpu"].sql(sql).collect(),
                 ctxs["cpu"].sql(sql).collect(), sql)


@pytest.mark.parametrize("seed", range(8))
def test_fuzz_aggregate_over_join(tmp_path, seed):
    """Random star joins through the factagg/mapped admission machinery."""
    rng = np.random.default_rng(2000 + seed)
    _fresh()
    nk = int(rng.integers(50, 2000))
    nf = int(rng.integers(2_000, 30_000))
    missing = int(rng.integers(0, nk // 4 + 1))
    fact = pa.table(
        {
            "fk": pa.array(rng.integers(0, nk + missing, nf),
                           type=pa.int64()),
            "v": pa.array(np.round(rng.uniform(0, 500, nf), 2)),
            "q": pa.array(rng.integers(1, 50, nf), type=pa.int64()),
            "m": pa.array([f"m{x}" for x in rng.integers(0, 6, nf)]),
        }
    )
    dim = pa.table(
        {
            "dk": pa.array(np.arange(nk), type=pa.int64()),
            "attr": pa.array([f"g{i % rng.integers(2, 40)}"
                              for i in range(nk)]),
            "w": pa.array(rng.integers(0, 10, nk), type=pa.int64()),
        }
    )
    pq.write_table(fact, str(tmp_path / "fact.parquet"))
    pq.write_table(dim, str(tmp_path / "dim.parquet"))
    ctxs = {}
    for backend in ("tpu", "cpu"):
        ctx = ExecutionContext(
            BallistaConfig({"ballista.executor.backend": backend})
        )
        ctx.register_parquet("fact", str(tmp_path / "fact.parquet"))
        ctx.register_parquet("dim", str(tmp_path / "dim.parquet"))
        ctxs[backend] = ctx

    group = rng.choice(["fk", "attr", "m", "fk, attr", "attr, m"])
    _JOIN_AGGS = [("sum(v)", False), ("count(*)", True), ("sum(q)", True),
                  ("avg(v)", False), ("sum(v * q)", False),
                  ("sum(case when attr <> 'g1' then v else 0 end)", False),
                  ("sum(w)", True), ("min(q)", True), ("max(q)", True)]
    picks = list(rng.choice(len(_JOIN_AGGS), size=rng.integers(1, 4),
                            replace=False))
    sel = ", ".join([group] + [f"{_JOIN_AGGS[p][0]} as a{i}"
                               for i, p in enumerate(picks)])
    sql = f"select {sel} from dim, fact where dk = fk"
    if rng.random() < 0.6:
        sql += " and " + str(rng.choice(
            ["v > 100", "q < 25", "m <> 'm3'", "w > 2"]
        ))
    sql += f" group by {group}"
    exact = [f"a{i}" for i, p in enumerate(picks) if _JOIN_AGGS[p][1]]
    if exact and rng.random() < 0.5:
        # Sort+Limit epilogue through the factagg/mapped top-k machinery
        # (ties included; trailing group keys make the order total)
        rank = f"{rng.choice(exact)}{' desc' if rng.random() < 0.5 else ''}"
        sql += f" order by {rank}, {group} limit {rng.integers(1, 40)}"
    else:
        sql += f" order by {group}"
    _compare(ctxs["tpu"].sql(sql).collect(),
             ctxs["cpu"].sql(sql).collect(), sql)


def _dup_key_build(rng, shape: str):
    """Build-side key column with controlled duplicate-key structure.

    Shapes (ROADMAP "outer joins with duplicate keys" fuzzer slice):
    - zipf: Zipf-skewed duplicate counts clipped inside the admission tiers
      (the heaviest device-admissible skew);
    - all_dup: every row carries ONE key (multiplicity == num_rows);
    - monster: mostly-unique keys plus one key duplicated past the top
      tier, forcing the step-aside path (results must still be exact);
    - uniform: modest uniform duplication (the common case)."""
    from ballista_tpu.ops.kernels import JOIN_MULTIPLICITY_TIERS

    top = JOIN_MULTIPLICITY_TIERS[-1]
    nk = int(rng.integers(30, 400))
    if shape == "zipf":
        counts = np.minimum(rng.zipf(1.5, nk), top)
        keys = np.repeat(np.arange(nk, dtype=np.int64), counts)
    elif shape == "all_dup":
        keys = np.full(int(rng.integers(2, min(top, 150))), 7, dtype=np.int64)
    elif shape == "monster":
        keys = np.concatenate([
            np.arange(nk, dtype=np.int64),
            np.full(top + int(rng.integers(1, 50)), 3, dtype=np.int64),
        ])
    else:  # uniform
        keys = np.repeat(
            np.arange(nk, dtype=np.int64), rng.integers(1, 6, nk)
        )
    rng.shuffle(keys)
    return keys


@pytest.mark.parametrize("seed", range(8))
def test_fuzz_duplicate_key_joins(tmp_path, seed):
    """Differential duplicate-key join sweep: the M:N device kernel (INNER,
    build side with duplicate keys) and the host LEFT join must agree with
    the cpu backend bit-for-bit — multiplicity, order, and null padding
    included. Own rng streams (12000+/13000+ seeds), so every baseline
    generator above stays byte-identical."""
    rng = np.random.default_rng(12000 + seed)
    prng = np.random.default_rng(13000 + seed)
    _fresh()
    shape = str(rng.choice(["zipf", "all_dup", "monster", "uniform"]))
    bkeys = _dup_key_build(rng, shape)
    nb = len(bkeys)
    # ~5% null build keys (nulls must never match, not even each other)
    bnull = rng.random(nb) < 0.05
    build = pa.table(
        {
            "bk": pa.array(
                [None if isnull else int(v) for v, isnull in zip(bkeys, bnull)],
                type=pa.int64(),
            ),
            "bv": pa.array(np.round(rng.uniform(-100, 100, nb), 3)),
            "bs": pa.array([f"b{v % 11}" for v in range(nb)]),
        }
    )
    np_rows = int(prng.integers(500, 8000))
    pkeys = prng.integers(-1, int(bkeys.max()) + 20, np_rows)
    probe = pa.table(
        {
            "pk": pa.array(
                [None if v < 0 else int(v) for v in pkeys], type=pa.int64()
            ),
            "pv": pa.array(np.round(prng.uniform(0, 50, np_rows), 3)),
        }
    )
    how = str(rng.choice(["inner", "left"]))
    out = {}
    for backend in ("tpu", "cpu"):
        ctx = ExecutionContext(
            BallistaConfig({"ballista.executor.backend": backend})
        )
        ctx.register_record_batches("b", build, n_partitions=1)
        ctx.register_record_batches("p", probe, n_partitions=1)
        df = ctx.table("b").join(ctx.table("p"), ["bk"], ["pk"], how=how)
        out[backend] = df.collect()
    assert out["tpu"].schema == out["cpu"].schema, (shape, how)
    assert out["tpu"].to_pylist() == out["cpu"].to_pylist(), (shape, how)


def _distributed_fuzz_queries(qrng, k=2):
    """Random 2-stage (partial agg -> shuffle -> final agg) queries from the
    dedicated 15000+ stream. Aggregates restricted to orders the
    distributed fold computes deterministically under retries (it does for
    all of them — partials are per-partition and partitioning is by hash)."""
    aggs = ["sum(v)", "count(*)", "min(q)", "max(q)", "sum(q)"]
    out = []
    for _ in range(k):
        key = str(qrng.choice(["g", "s", "g, s"]))
        picks = list(qrng.choice(aggs, size=int(qrng.integers(1, 4)),
                                 replace=False))
        sel = ", ".join([key] + [f"{a} as a{i}" for i, a in enumerate(picks)])
        sql = f"select {sel} from t"
        if qrng.random() < 0.5:
            sql += " where " + str(qrng.choice(
                ["v > 0", "q < 30", "s <> 't2'", "g % 7 <> 3"]
            ))
        out.append(sql + f" group by {key} order by {key}")
    return out


def _run_distributed(table, queries, client_settings, cluster_config=None):
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.executor.runtime import StandaloneCluster

    cluster = StandaloneCluster(n_executors=2, config=cluster_config)
    try:
        ctx = BallistaContext(*cluster.scheduler_addr, settings=client_settings)
        ctx.register_record_batches("t", table, n_partitions=4)
        out = [ctx.sql(sql).collect() for sql in queries]
        ctx.close()
        return out
    finally:
        cluster.shutdown()


@pytest.mark.parametrize("seed", range(2))
def test_fuzz_distributed_two_stage_chaos(seed):
    """ROADMAP fuzzer slice (ISSUE 6 satellite): random 2-stage plans
    through the REAL scheduler + executors, run fault-free and then with
    the PR 5/6 chaos sites armed at a seeded nonzero rate — task faults,
    fetch faults, scheduler KV-write faults, and torn planning writes must
    all recover to BIT-IDENTICAL results. Own rng streams (14000+ data,
    15000+ queries), so every baseline stream above stays byte-identical."""
    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.ops.runtime import recovery_stats

    rng = np.random.default_rng(14000 + seed)
    qrng = np.random.default_rng(15000 + seed)
    _fresh()
    n = int(rng.integers(2_000, 8_000))
    table = pa.table(
        {
            "g": pa.array(rng.integers(0, 50, n), type=pa.int64()),
            "v": pa.array(np.round(rng.uniform(-100, 100, n), 2)),
            "q": pa.array(rng.integers(1, 50, n), type=pa.int64()),
            "s": pa.array([f"t{x}" for x in rng.integers(0, 5, n)]),
        }
    )
    queries = _distributed_fuzz_queries(qrng)

    clean = _run_distributed(
        table, queries, {"ballista.shuffle.partitions": "4"}
    )
    # executor-side sites ride the per-job client settings; scheduler-side
    # sites (kv.put, scheduler.plan_write) arm through the cluster config
    chaos_client = {
        "ballista.shuffle.partitions": "4",
        "ballista.chaos.rate": "0.05",
        "ballista.chaos.seed": str(70 + seed),
        "ballista.chaos.sites": "task.execute,flight.fetch",
        "ballista.shuffle.max_task_retries": "5",
    }
    chaos_cluster = BallistaConfig({
        "ballista.chaos.rate": "0.02",
        "ballista.chaos.seed": str(70 + seed),
        "ballista.chaos.sites": "kv.put,scheduler.plan_write",
        "ballista.shuffle.max_task_retries": "5",
    })
    recovery_stats(reset=True)
    chaotic = _run_distributed(table, queries, chaos_client, chaos_cluster)
    stats = recovery_stats(reset=True)
    for sql, c, t in zip(queries, clean, chaotic):
        assert t.equals(c), (sql, t.to_pydict(), c.to_pydict())
    assert stats.get("chaos_injected", 0) > 0, stats


@pytest.mark.parametrize("seed", range(2))
def test_fuzz_concurrent_submission_cache(seed):
    """Multi-tenant fuzz slice (ISSUE 7 satellite): N concurrent tenant
    clients replay a Zipf-repeated random query mix against ONE cluster
    with the result cache armed; every result — cache-served or cold —
    must be bit-identical to a cache-disabled sequential baseline, and the
    Zipf repetition must actually produce hits. Own rng streams (16000+
    data, 17000+ queries/replay), so every baseline stream above stays
    byte-identical."""
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.executor.runtime import StandaloneCluster
    from ballista_tpu.ops.runtime import tenancy_stats

    rng = np.random.default_rng(16000 + seed)
    qrng = np.random.default_rng(17000 + seed)
    _fresh()
    n = int(rng.integers(2_000, 6_000))
    table = pa.table(
        {
            "g": pa.array(rng.integers(0, 40, n), type=pa.int64()),
            "v": pa.array(np.round(rng.uniform(-100, 100, n), 2)),
            "q": pa.array(rng.integers(1, 50, n), type=pa.int64()),
            "s": pa.array([f"t{x}" for x in rng.integers(0, 5, n)]),
        }
    )
    queries = _distributed_fuzz_queries(qrng, k=4)
    # Zipf-repeated replay schedules, drawn BEFORE any threading so the
    # schedule is a pure function of the seed
    n_tenants = 4
    schedules = [
        [int(z - 1) % len(queries)
         for z in qrng.zipf(1.6, size=int(qrng.integers(4, 7)))]
        for _ in range(n_tenants)
    ]
    cold = _run_distributed(
        table, queries,
        {"ballista.cache.results": "false", "ballista.shuffle.partitions": "4"},
    )
    cluster = StandaloneCluster(n_executors=2)
    try:
        tenancy_stats(reset=True)
        results = {}
        errors = []

        def replay(i):
            try:
                ctx = BallistaContext(
                    *cluster.scheduler_addr,
                    settings={
                        "ballista.tenant.name": f"tenant{i}",
                        "ballista.shuffle.partitions": "4",
                    },
                )
                ctx.register_record_batches("t", table, n_partitions=4)
                results[i] = [
                    (qi, ctx.sql(queries[qi]).collect())
                    for qi in schedules[i]
                ]
                ctx.close()
            except Exception as e:  # surface in the main thread
                errors.append((i, e))

        import threading

        threads = [
            threading.Thread(target=replay, args=(i,))
            for i in range(n_tenants)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(180)
        assert not errors, errors
        for i in range(n_tenants):
            for qi, got in results[i]:
                assert got.equals(cold[qi]), (
                    i, queries[qi], got.to_pydict(), cold[qi].to_pydict()
                )
        stats = tenancy_stats(reset=True)
        total = sum(len(s) for s in schedules)
        assert stats.get("cache_hit", 0) > 0, (stats, schedules)
        assert stats.get("cache_hit", 0) + stats.get("cache_miss", 0) >= total
    finally:
        cluster.shutdown()


@pytest.mark.parametrize("seed", range(4))
def test_fuzz_float_extrema_minmax(tmp_path, seed):
    """Dedicated float-extrema sweep: MIN/MAX over NaN/±0/subnormal/
    negative-heavy doubles must agree across backends — bit-exactly when
    the device path runs (the bijection), and via the host fallback when
    NaN forces the decline. High-cardinality groups keep this on the
    vectorized sorted core."""
    rng = np.random.default_rng(8000 + seed)
    _fresh()
    n = int(rng.integers(5_000, 30_000))
    fx = _extrema_floats(rng, n)
    table = pa.table(
        {
            "g": pa.array(rng.integers(0, 2000, n), type=pa.int64()),
            "fx": pa.array(fx),
            "q": pa.array(rng.integers(1, 50, n), type=pa.int64()),
        }
    )
    path = str(tmp_path / "t.parquet")
    pq.write_table(table, path)
    ctxs = {}
    for backend in ("tpu", "cpu"):
        ctx = ExecutionContext(
            BallistaConfig({"ballista.executor.backend": backend})
        )
        ctx.register_parquet("t", path)
        ctxs[backend] = ctx
    queries = [
        "select min(fx) as mn, max(fx) as mx from t",
        "select g, min(fx) as mn, max(fx) as mx from t group by g order by g",
        ("select g, min(fx) as mn, count(*) as c from t where q < 40 "
         "group by g order by mn, g limit 25"),
    ]
    for sql in queries:
        t = ctxs["tpu"].sql(sql).collect().to_pydict()
        c = ctxs["cpu"].sql(sql).collect().to_pydict()
        assert set(t) == set(c), sql
        for name in t:
            for a, b in zip(t[name], c[name]):
                if isinstance(a, float) and isinstance(b, float):
                    # bit-exact modulo the documented ±0 collapse
                    assert (a == b == 0.0) or (
                        np.float64(a).tobytes() == np.float64(b).tobytes()
                    ), (sql, name, a, b)
                else:
                    assert a == b, (sql, name, a, b)


@pytest.mark.parametrize("seed", range(2))
def test_fuzz_speculation_straggler(seed):
    """Speculation fuzz slice (ISSUE 11 satellite): random 2-stage plans
    through the REAL scheduler + executors under seeded `task.slow` chaos
    with speculation ARMED (aggressive thresholds, predictions warmed by
    the fault-free pass — the task.run op is job-independent, so the clean
    run's durations predict the chaos run's). The straggler site never
    corrupts work, and first-completion-wins must never double-count it:
    results are BIT-IDENTICAL to the fault-free baseline whatever the
    duplicate/primary race does. Own rng streams (20000+ data, 21000+
    queries), so every baseline stream above stays byte-identical."""
    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.ops import costmodel
    from ballista_tpu.ops.runtime import recovery_stats, speculation_stats

    rng = np.random.default_rng(20000 + seed)
    qrng = np.random.default_rng(21000 + seed)
    _fresh()
    costmodel.reset()
    n = int(rng.integers(2_000, 8_000))
    table = pa.table(
        {
            "g": pa.array(rng.integers(0, 50, n), type=pa.int64()),
            "v": pa.array(np.round(rng.uniform(-100, 100, n), 2)),
            "q": pa.array(rng.integers(1, 50, n), type=pa.int64()),
            "s": pa.array([f"t{x}" for x in rng.integers(0, 5, n)]),
        }
    )
    queries = _distributed_fuzz_queries(qrng)
    # the in-memory cost store (dir "") is process-global: the clean pass
    # warms the task.run rates the chaos pass's straggler monitor predicts
    # from — every config (cluster AND per-job) pins the same dir so no
    # configure() rebind drops the store between the two passes
    spec_cluster = BallistaConfig({
        "ballista.tpu.cost_model_dir": "",
        "ballista.speculation.min_runtime_ms": "100",
        "ballista.speculation.multiplier": "2",
    })
    base_client = {
        "ballista.shuffle.partitions": "4",
        "ballista.cache.results": "false",
        "ballista.tpu.cost_model_dir": "",
    }
    clean = _run_distributed(table, queries, base_client, spec_cluster)
    chaos_client = {
        **base_client,
        "ballista.chaos.rate": "0.2",
        "ballista.chaos.seed": str(90 + seed),
        "ballista.chaos.sites": "task.slow",
        "ballista.chaos.slow_ms": "2000",
    }
    recovery_stats(reset=True)
    speculation_stats(reset=True)
    chaotic = _run_distributed(table, queries, chaos_client, spec_cluster)
    rec = recovery_stats(reset=True)
    spec = speculation_stats(reset=True)
    costmodel.reset()
    for sql, c, t in zip(queries, clean, chaotic):
        assert t.equals(c), (sql, t.to_pydict(), c.to_pydict())
    assert rec.get("chaos_slow_injected", 0) > 0, rec
    assert spec.get("launched", 0) >= 1, (spec, rec)


@pytest.mark.parametrize("seed", range(6))
def test_fuzz_routing(tmp_path, seed):
    """Adaptive-execution replay (ISSUE 10): the duplicate-key join sweep
    re-run with the cost model forced cold, warm, off, and fed seeded
    ADVERSARIAL cost entries (absurd rates both directions). Routing may
    differ — device, split, extended tier, host — but results must be
    bit-identical in every configuration: the cost model changes where a
    partition runs, never what it returns. Own rng streams (18000+ data,
    19000+ probe/adversary), so every baseline stream above stays
    byte-identical."""
    from ballista_tpu.ops import costmodel
    from ballista_tpu.ops.kernels import JOIN_EXTENDED_TIERS

    rng = np.random.default_rng(18000 + seed)
    prng = np.random.default_rng(19000 + seed)
    _fresh()
    costmodel.reset(clear_dir=True)
    shape = str(rng.choice(["zipf", "all_dup", "monster", "uniform"]))
    bkeys = _dup_key_build(rng, shape)
    nb = len(bkeys)
    bnull = rng.random(nb) < 0.05
    build = pa.table({
        "bk": pa.array(
            [None if isnull else int(v) for v, isnull in zip(bkeys, bnull)],
            type=pa.int64(),
        ),
        "bv": pa.array(np.round(rng.uniform(-100, 100, nb), 3)),
    })
    np_rows = int(prng.integers(500, 6000))
    pkeys = prng.integers(-1, int(bkeys.max()) + 20, np_rows)
    probe = pa.table({
        "pk": pa.array(
            [None if v < 0 else int(v) for v in pkeys], type=pa.int64()
        ),
        "pv": pa.array(np.round(prng.uniform(0, 50, np_rows), 3)),
    })

    def run(backend, model, store_dir):
        ctx = ExecutionContext(BallistaConfig({
            "ballista.executor.backend": backend,
            "ballista.tpu.cost_model": model,
            "ballista.tpu.cost_model_dir": store_dir,
        }))
        ctx.register_record_batches("b", build, n_partitions=1)
        ctx.register_record_batches("p", probe, n_partitions=1)
        df = ctx.table("b").join(ctx.table("p"), ["bk"], ["pk"], how="inner")
        return df.collect().to_pylist()

    store = str(tmp_path / "costs")
    try:
        baseline = run("cpu", "false", "")
        out_off = run("tpu", "false", "")
        out_cold = run("tpu", "true", store)
        costmodel.flush()
        costmodel.reset()  # fresh-process simulation: reload from disk
        out_warm = run("tpu", "true", store)
        # adversarial entries: absurd rates in a prng-chosen direction,
        # covering every op the join ladder predicts from. The run MUST
        # keep the same store dir — a dir change in configure() clears the
        # in-memory store and would silently wipe the seeds
        fast, slow = (1e-12, 100.0)
        if prng.random() < 0.5:
            fast, slow = slow, fast
        for tier in JOIN_EXTENDED_TIERS:
            costmodel.seed("join.gather", 4096 * tier, fast)
        costmodel.seed("join.gather", 4096, fast)
        costmodel.seed("join.host", nb + np_rows, slow, engine="host")
        assert costmodel.snapshot(), "adversarial seeds must be installed"
        out_adv = run("tpu", "true", store)
        assert costmodel.snapshot(), "seeds were wiped before the run"
        assert baseline == out_off == out_cold == out_warm == out_adv, (
            shape, seed,
        )
    finally:
        costmodel.reset(clear_dir=True)
        _fresh()


@pytest.mark.parametrize("seed", range(2))
def test_fuzz_shared_tier_chaos(seed, tmp_path):
    """Shared-tier fuzz slice (ISSUE 15 satellite): random 2-stage plans on
    the SHARED shuffle tier under seeded shuffle.store chaos (torn storage
    publishes retry; torn storage reads degrade down the peer/lineage
    ladder) PLUS a deterministic mid-run executor death — results must be
    bit-identical to the LOCAL-tier fault-free baseline. Own rng streams
    (24000+ data, 25000+ queries), so every baseline stream above stays
    byte-identical."""
    import time as _time

    import ballista_tpu.scheduler.state as state_mod
    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.ops.runtime import recovery_stats, shuffle_tier_stats
    from ballista_tpu.utils.chaos import ChaosInjector

    rng = np.random.default_rng(24000 + seed)
    qrng = np.random.default_rng(25000 + seed)
    _fresh()
    n = int(rng.integers(2_000, 8_000))
    table = pa.table(
        {
            "g": pa.array(rng.integers(0, 50, n), type=pa.int64()),
            "v": pa.array(np.round(rng.uniform(-100, 100, n), 2)),
            "q": pa.array(rng.integers(1, 50, n), type=pa.int64()),
            "s": pa.array([f"t{x}" for x in rng.integers(0, 5, n)]),
        }
    )
    queries = _distributed_fuzz_queries(qrng)

    clean = _run_distributed(
        table, queries, {"ballista.shuffle.partitions": "4"}
    )

    # deterministic executor death: local-0 dies within its first polls,
    # local-1 survives the whole run (pure hashing, stable forever)
    death_seed = None
    for cand in range(2000):
        inj = ChaosInjector(cand, 0.005, sites={"executor.death"})

        def death_poll(eid, horizon):
            for k in range(1, horizon):
                if inj.should_inject("executor.death", f"{eid}/poll{k}"):
                    return k
            return None

        d0 = death_poll("local-0", 17)
        if d0 is not None and 4 <= d0 and death_poll("local-1", 400) is None:
            death_seed = cand
            break
    assert death_seed is not None, "no death seed in scan range"

    shared = str(tmp_path / f"store{seed}")
    chaos_client = {
        "ballista.shuffle.partitions": "4",
        "ballista.shuffle.tier": "shared",
        "ballista.shuffle.dir": shared,
        # this slice exercises the STORAGE ladder under torn publishes —
        # the ISSUE 16 residency registry would satisfy same-executor
        # reads before the ladder (and shift the poll cadence the death
        # seed was scanned for); test_fuzz_exchange_chaos owns the
        # exchange-on chaos story
        "ballista.tpu.exchange": "false",
        "ballista.chaos.rate": "0.05",
        "ballista.chaos.seed": str(170 + seed),
        "ballista.chaos.sites": "shuffle.store",
        "ballista.shuffle.max_task_retries": "5",
    }
    chaos_cluster = BallistaConfig({
        "ballista.chaos.rate": "0.005",
        "ballista.chaos.seed": str(death_seed),
        "ballista.chaos.sites": "executor.death",
        "ballista.shuffle.max_task_retries": "5",
    })
    old_lease = state_mod.EXECUTOR_LEASE_SECS
    state_mod.EXECUTOR_LEASE_SECS = 1.0
    recovery_stats(reset=True)
    shuffle_tier_stats(reset=True)
    try:
        chaotic = _run_distributed(table, queries, chaos_client, chaos_cluster)
    finally:
        state_mod.EXECUTOR_LEASE_SECS = old_lease
    stats = recovery_stats(reset=True)
    tier = shuffle_tier_stats(reset=True)
    for sql, c, t in zip(queries, clean, chaotic):
        assert t.equals(c), (sql, t.to_pydict(), c.to_pydict())
    assert stats.get("chaos_injected", 0) > 0, stats
    assert stats.get("chaos_executor_death", 0) >= 1, stats
    assert tier.get("storage_publish", 0) > 0, tier
    assert tier.get("storage_fetch", 0) > 0, tier


@pytest.mark.parametrize("seed", range(2))
def test_fuzz_exchange_chaos(seed):
    """HBM-resident exchange fuzz slice (ISSUE 16 satellite): random
    2-stage plans run fault-free with the exchange OFF (pure authoritative
    piece ladder — the oracle), then with the exchange ON under seeded
    exchange.evict chaos (consume-time registry probes torn) PLUS a
    deterministic mid-run executor death (the registry dies with its
    executor). The residency tier is pure acceleration: every loss
    degrades to the ladder, so results must be bit-identical. Own rng
    streams (26000+ data, 27000+ queries), so every baseline stream above
    stays byte-identical."""
    import ballista_tpu.scheduler.state as state_mod
    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.ops import exchange
    from ballista_tpu.ops.runtime import exchange_stats, recovery_stats
    from ballista_tpu.utils.chaos import ChaosInjector

    rng = np.random.default_rng(26000 + seed)
    qrng = np.random.default_rng(27000 + seed)
    _fresh()
    n = int(rng.integers(2_000, 8_000))
    table = pa.table(
        {
            "g": pa.array(rng.integers(0, 50, n), type=pa.int64()),
            "v": pa.array(np.round(rng.uniform(-100, 100, n), 2)),
            "q": pa.array(rng.integers(1, 50, n), type=pa.int64()),
            "s": pa.array([f"t{x}" for x in rng.integers(0, 5, n)]),
        }
    )
    queries = _distributed_fuzz_queries(qrng)

    clean = _run_distributed(
        table, queries,
        {"ballista.shuffle.partitions": "4",
         "ballista.tpu.exchange": "false"},
    )

    # deterministic executor death: local-0 dies within its first polls,
    # local-1 survives the whole run (pure hashing, stable forever)
    death_seed = None
    for cand in range(2000):
        inj = ChaosInjector(cand, 0.005, sites={"executor.death"})

        def death_poll(eid, horizon):
            for k in range(1, horizon):
                if inj.should_inject("executor.death", f"{eid}/poll{k}"):
                    return k
            return None

        d0 = death_poll("local-0", 17)
        if d0 is not None and 4 <= d0 and death_poll("local-1", 400) is None:
            death_seed = cand
            break
    assert death_seed is not None, "no death seed in scan range"

    chaos_client = {
        "ballista.shuffle.partitions": "4",
        "ballista.chaos.rate": "0.3",
        "ballista.chaos.seed": str(190 + seed),
        "ballista.chaos.sites": "exchange.evict",
        "ballista.shuffle.max_task_retries": "5",
    }
    chaos_cluster = BallistaConfig({
        "ballista.chaos.rate": "0.005",
        "ballista.chaos.seed": str(death_seed),
        "ballista.chaos.sites": "executor.death",
        "ballista.shuffle.max_task_retries": "5",
    })
    old_lease = state_mod.EXECUTOR_LEASE_SECS
    state_mod.EXECUTOR_LEASE_SECS = 1.0
    exchange.reset()
    exchange_stats(reset=True)
    recovery_stats(reset=True)
    try:
        chaotic = _run_distributed(table, queries, chaos_client, chaos_cluster)
    finally:
        state_mod.EXECUTOR_LEASE_SECS = old_lease
    stats = recovery_stats(reset=True)
    ex = exchange_stats(reset=True)
    for sql, c, t in zip(queries, clean, chaotic):
        assert t.equals(c), (sql, t.to_pydict(), c.to_pydict())
    assert stats.get("chaos_injected", 0) > 0, stats
    assert stats.get("chaos_executor_death", 0) >= 1, stats
    # the registry was exercised AND torn: publishes happened, at least
    # one probe lost its entry to chaos, and the reads that missed walked
    # the ladder instead of failing the task
    assert ex.get("published", 0) > 0, ex
    assert ex.get("evicted_chaos", 0) >= 1, ex


# ---------------------------------------------------------------------------
# ISSUE 19: incremental execution under randomized appends
# ---------------------------------------------------------------------------


def _delta_fuzz_queries(qrng):
    """Randomized advancement-shaped aggregations plus one deliberately
    INELIGIBLE member set (a float sum must decline, never mis-fold)."""
    queries = []
    for _ in range(3):
        members = ["count(*) as c"]
        if qrng.integers(0, 2):
            members.append("sum(v) as sv")
        if qrng.integers(0, 2):
            members.append("min(v) as mn")
        if qrng.integers(0, 2):
            members.append("max(v) as mx")
        keys = "g, h" if qrng.integers(0, 2) else "g"
        thr = int(qrng.integers(-8, 2))
        queries.append(
            f"select {keys}, {', '.join(members)} from t where w > {thr} "
            f"group by {keys} order by {keys}"
        )
    queries.append(
        "select g, sum(f) as sf, count(*) as c from t "
        "group by g order by g"
    )
    return queries


@pytest.mark.parametrize("seed", range(3))
def test_fuzz_delta_append(tmp_path, seed):
    """ROADMAP fuzzer slice (ISSUE 19): randomized eligible and ineligible
    aggregations over a parquet set that GROWS mid-stream, with the result
    cache advancing on the appends — fault-free and with every advanced
    publish torn by cache.advance chaos. Every configuration must be
    bit-identical to a cold full run over the grown set; the ineligible
    member set (float sum) must decline, never mis-fold. Own rng streams
    (28000+ data, 29000+ queries), so every baseline stream above stays
    byte-identical."""
    import os

    from ballista_tpu.client import BallistaContext
    from ballista_tpu.executor.runtime import StandaloneCluster
    from ballista_tpu.ops.runtime import delta_stats

    rng = np.random.default_rng(28000 + seed)
    qrng = np.random.default_rng(29000 + seed)
    d = str(tmp_path / "grow")
    os.makedirs(d)

    def write_part(i):
        n = int(rng.integers(1_000, 4_000))
        pq.write_table(pa.table({
            "g": pa.array(rng.integers(0, 9, n), type=pa.int64()),
            "h": pa.array(rng.integers(0, 3, n), type=pa.int64()),
            "v": pa.array(rng.integers(-100, 100, n), type=pa.int64()),
            "w": pa.array(rng.integers(-10, 10, n), type=pa.int64()),
            "f": pa.array(rng.random(n), type=pa.float64()),
        }), os.path.join(d, f"part-{i}.parquet"))

    write_part(0)
    write_part(1)
    queries = _delta_fuzz_queries(qrng)
    next_part = [2]

    def run_grow(cluster_config=None):
        """Cold pass over the current set, append one NEW file (never a
        rewrite — a moved identity is a correct probe miss, not a delta),
        advanced pass over the grown set."""
        cluster = StandaloneCluster(n_executors=2, config=cluster_config)
        try:
            ctx = BallistaContext(*cluster.scheduler_addr, settings={
                "ballista.cache.advance": "true",
            })
            ctx.register_parquet("t", d)
            for sql in queries:
                ctx.sql(sql).collect()
            write_part(next_part[0])
            next_part[0] += 1
            ctx.register_parquet("t", d)
            grown = [ctx.sql(sql).collect() for sql in queries]
            truth_ctx = BallistaContext(*cluster.scheduler_addr, settings={
                "ballista.cache.results": "false",
            })
            truth_ctx.register_parquet("t", d)
            truth = [truth_ctx.sql(sql).collect() for sql in queries]
            ctx.close()
            truth_ctx.close()
            return grown, truth
        finally:
            cluster.shutdown()

    delta_stats(reset=True)
    grown, truth = run_grow()
    stats = delta_stats(reset=True)
    for sql, g, t in zip(queries, grown, truth):
        assert g.equals(t), (sql, g.to_pydict(), t.to_pydict())
    # the eligible shapes advanced; the float-sum shape declined loudly
    assert stats.get("advance_hits", 0) >= 1, stats
    assert stats.get("advance_declined", 0) >= 1, stats

    # every advanced publish torn: all declines, still bit-identical. The
    # chaos pass's cold queries hit the first pass's (shared content-key)
    # cache entries; its append then forces a NEW advancement attempt
    # whose publish the chaos site tears.
    delta_stats(reset=True)
    chaos_grown, chaos_truth = run_grow(BallistaConfig({
        "ballista.chaos.rate": "1.0",
        "ballista.chaos.seed": str(70 + seed),
        "ballista.chaos.sites": "cache.advance",
    }))
    stats = delta_stats(reset=True)
    for sql, g, t in zip(queries, chaos_grown, chaos_truth):
        assert g.equals(t), (sql, g.to_pydict(), t.to_pydict())
    assert stats.get("advance_hits", 0) == 0, stats
    assert stats.get("advance_declined", 0) >= 1, stats


@pytest.mark.parametrize("seed", range(2))
def test_fuzz_replica_failover(seed):
    """ROADMAP fuzzer slice (ISSUE 20 satellite): random 2-stage plans
    against a 2-replica control plane with the ``scheduler.lease`` chaos
    site armed (torn renewal rounds lapse owned leases early, so peers
    adopt live jobs) PLUS a seeded hard kill of replica 0 partway through
    the query stream. Every query must come back BIT-IDENTICAL to the
    fault-free single-scheduler oracle. Chaos verdicts on renewal rounds
    are timing-dependent (rounds tick on the wall clock), so this slice
    asserts results, not injection counters — the deterministic owner
    kill is the headline. Own rng streams (30000+ data, 31000+ queries),
    so every baseline stream above stays byte-identical."""
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.executor.runtime import StandaloneCluster
    from ballista_tpu.ops.runtime import recovery_stats

    rng = np.random.default_rng(30000 + seed)
    qrng = np.random.default_rng(31000 + seed)
    _fresh()
    n = int(rng.integers(2_000, 6_000))
    table = pa.table(
        {
            "g": pa.array(rng.integers(0, 50, n), type=pa.int64()),
            "v": pa.array(np.round(rng.uniform(-100, 100, n), 2)),
            "q": pa.array(rng.integers(1, 50, n), type=pa.int64()),
            "s": pa.array([f"t{x}" for x in rng.integers(0, 5, n)]),
        }
    )
    queries = _distributed_fuzz_queries(qrng, k=3)
    kill_after = int(rng.integers(1, len(queries)))

    oracle = _run_distributed(
        table, queries, {"ballista.shuffle.partitions": "4"}
    )

    _fresh()
    recovery_stats(reset=True)
    cluster = StandaloneCluster(
        n_executors=2,
        n_schedulers=2,
        config=BallistaConfig({
            "ballista.scheduler.lease_ttl_s": "0.3",
            "ballista.chaos.rate": "0.25",
            "ballista.chaos.seed": str(90 + seed),
            "ballista.chaos.sites": "scheduler.lease",
        }),
    )
    try:
        ctx = BallistaContext(
            *cluster.scheduler_addr,
            settings={"ballista.shuffle.partitions": "4"},
            endpoints=cluster.scheduler_endpoints,
        )
        ctx.register_record_batches("t", table, n_partitions=4)
        got = []
        for i, sql in enumerate(queries):
            if i == kill_after:
                cluster.kill_scheduler(0)
            got.append(ctx.sql(sql).collect())
        ctx.close()
    finally:
        cluster.shutdown()

    for sql, g, o in zip(queries, got, oracle):
        assert g.equals(o), (seed, kill_after, sql,
                             g.to_pydict(), o.to_pydict())
    stats = recovery_stats(reset=True)
    # the survivor finished every post-kill query without a single task
    # re-execution: failover is a control-plane event, not a data redo
    assert stats.get("task_retry", 0) == 0, stats

"""Concurrency analyzer (ISSUE 14): static lock-order graph, deadlock
detection, manifest enforcement, atomicity check, the dynamic lock
witness, the witness-vs-static cross-check, and --jobs parallel analysis.

The production gate itself — `python -m dev.analysis` clean with the
lock-order rule enabled — lives in test_static_analysis.py; this file
exercises the machinery."""

import json
import pathlib
import subprocess
import sys
import textwrap
import threading

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
FIXTURES = pathlib.Path(__file__).resolve().parent / "fixtures" / "lint"
sys.path.insert(0, str(REPO))

from dev.analysis import lockgraph  # noqa: E402
from dev.analysis.core import analyze_file, run_paths  # noqa: E402
from dev.analysis.lockgraph import (  # noqa: E402
    EdgeSite,
    LockGraph,
    Manifest,
    diff_witness,
)
from dev.analysis.rules_lockorder import RULE, build_graph, static_edges  # noqa: E402
from ballista_tpu.utils import locks  # noqa: E402


def _site(src, dst, line=1, func="f", via=""):
    return EdgeSite(src, dst, "x.py", line, func, via)


def _graph_of(facts_src: dict):
    """build_graph over {display_path: module_source} inline sources."""
    from dev.analysis.core import SourceFile
    from dev.analysis.rules_lockorder import extract_facts

    facts = {}
    for path, src in facts_src.items():
        sf = SourceFile(path, textwrap.dedent(src), path)
        facts[path] = extract_facts(sf)
    return build_graph(facts)


# -- graph construction units ------------------------------------------------

def test_direct_nesting_edge():
    graph, _ = _graph_of({"ballista_tpu/ops/m.py": """
        from ballista_tpu.utils.locks import make_lock
        _a_lock = make_lock("ops.m._a_lock")
        _b_lock = make_lock("ops.m._b_lock")
        def f():
            with _a_lock:
                with _b_lock:
                    pass
    """})
    assert ("ops.m._a_lock", "ops.m._b_lock") in graph.edge_set()
    site = graph.site("ops.m._a_lock", "ops.m._b_lock")
    assert site.func == "f" and site.via == ""


def test_same_module_call_chain_edge():
    graph, _ = _graph_of({"ballista_tpu/ops/m.py": """
        from ballista_tpu.utils.locks import make_lock
        _a_lock = make_lock("ops.m._a_lock")
        _b_lock = make_lock("ops.m._b_lock")
        def helper():
            with _b_lock:
                pass
        def f():
            with _a_lock:
                helper()
    """})
    assert ("ops.m._a_lock", "ops.m._b_lock") in graph.edge_set()
    assert graph.site("ops.m._a_lock", "ops.m._b_lock").via == "helper()"


def test_holds_lock_entry_context_edge():
    graph, _ = _graph_of({"ballista_tpu/ops/m.py": """
        from ballista_tpu.utils.locks import make_lock
        _a_lock = make_lock("ops.m._a_lock")
        _b_lock = make_lock("ops.m._b_lock")
        # holds-lock: _a_lock
        def locked_helper():
            with _b_lock:
                pass
    """})
    assert ("ops.m._a_lock", "ops.m._b_lock") in graph.edge_set()


def test_cross_module_call_resolved_by_base_segment():
    graph, _ = _graph_of({
        "ballista_tpu/scheduler/st.py": """
            from ballista_tpu.utils.locks import make_lock
            def f(self):
                with self.kv.lock():
                    self.kv.put("k", b"v")
        """,
        "ballista_tpu/scheduler/kv.py": """
            from ballista_tpu.utils.locks import make_rlock
            class B:
                def __init__(self):
                    self._mu = make_rlock("scheduler.kv.lock")
                def put(self, k, v):
                    with self._mu:
                        pass
        """,
    })
    # kv.lock -> kv.lock is reentrant self-re-entry, NOT an edge
    assert ("scheduler.kv.lock", "scheduler.kv.lock") not in graph.edge_set()


def test_cross_module_unique_bare_name_resolution():
    graph, _ = _graph_of({
        "ballista_tpu/ops/a.py": """
            from ballista_tpu.utils.locks import make_lock
            _a_lock = make_lock("ops.a._a_lock")
            def f():
                with _a_lock:
                    record_thing(1)
        """,
        "ballista_tpu/ops/b.py": """
            from ballista_tpu.utils.locks import make_lock
            _b_lock = make_lock("ops.b._b_lock")
            def record_thing(n):
                with _b_lock:
                    pass
        """,
    })
    assert ("ops.a._a_lock", "ops.b._b_lock") in graph.edge_set()


def test_foreign_attribute_calls_do_not_resolve():
    """`self._cache.get(...)` under a lock must NOT paint an edge to some
    other module's lock-acquiring `get` (the phantom-kv.get regression)."""
    graph, _ = _graph_of({
        "ballista_tpu/ops/a.py": """
            from ballista_tpu.utils.locks import make_lock
            _a_lock = make_lock("ops.a._a_lock")
            class C:
                def f(self):
                    with _a_lock:
                        self._cache.get("k")
        """,
        "ballista_tpu/scheduler/kv.py": """
            from ballista_tpu.utils.locks import make_rlock
            class B:
                def __init__(self):
                    self._mu = make_rlock("scheduler.kv.lock")
                def get(self, k):
                    with self._mu:
                        pass
        """,
    })
    assert ("ops.a._a_lock", "scheduler.kv.lock") not in graph.edge_set()


def test_may_acquire_annotation_seeds_edges():
    graph, _ = _graph_of({"ballista_tpu/ops/m.py": """
        from ballista_tpu.utils.locks import make_lock
        _a_lock = make_lock("ops.m._a_lock")
        # may-acquire: ops.stage._prepare_lock
        def dynamic_dispatch(plan):
            plan.execute()
        def f(plan):
            with _a_lock:
                dynamic_dispatch(plan)
    """})
    assert ("ops.m._a_lock", "ops.stage._prepare_lock") in graph.edge_set()


# -- cycle detection ---------------------------------------------------------

def test_two_cycle_detected_with_both_paths():
    g = LockGraph()
    g.add(_site("a", "b", 1, "f"))
    g.add(_site("b", "a", 9, "g"))
    cycles = g.cycles()
    assert ["a", "b", "a"] in cycles
    report = g.cycle_report(["a", "b", "a"])
    assert "x.py:1 in f" in report and "x.py:9 in g" in report


def test_three_cycle_detected_once():
    g = LockGraph()
    for s, d in (("a", "b"), ("b", "c"), ("c", "a")):
        g.add(_site(s, d))
    cycles = g.cycles()
    assert len(cycles) == 1 and set(cycles[0]) == {"a", "b", "c"}


def test_dag_has_no_cycles():
    g = LockGraph()
    for s, d in (("a", "b"), ("a", "c"), ("b", "c")):
        g.add(_site(s, d))
    assert g.cycles() == []


# -- manifest ----------------------------------------------------------------

def test_manifest_roundtrip_of_real_file():
    m = Manifest.load()
    assert m.rank["scheduler.kv.lock"] == 0  # the outermost lock
    assert m.reentrant("scheduler.kv.lock")
    assert m.plan_tree("physical.join._build_lock")
    # dst_group expands: the join build lock reaches the stage substrate
    assert ("physical.join._build_lock", "ops.stage._prepare_lock") in m.declared
    # a declared edge with a reason
    assert m.declared[("scheduler.kv.lock", "scheduler.server._push_mu")]


def test_manifest_check_edge_semantics():
    m = Manifest({
        "order": ["a", "b"],
        "edges": [{"src": "a", "dst": "b", "reason": "r"}],
        "locks": {
            "r1": {"reentrant": True},
            "t1": {"instance_tree": "tree"},
            "p1": {"plan_tree": "plan"},
            "p2": {"plan_tree": "plan"},
        },
    })
    assert m.check_edge("a", "b") is None  # declared + forward
    assert "undeclared" in m.check_edge("b", "a")
    assert "undeclared" in m.check_edge("a", "c")
    assert m.check_edge("r1", "r1") is None  # reentrant self
    assert m.check_edge("t1", "t1") is None  # instance-tree self
    assert "self-deadlock" in m.check_edge("a", "a")
    assert m.check_edge("p1", "p2") is None  # plan-tree pair exempt
    m2 = Manifest({"order": ["b"], "edges": [{"src": "a", "dst": "b"}]})
    assert "missing from the canonical `order`" in m2.check_edge("a", "b")


def test_manifest_inversion_detected():
    m = Manifest({
        "order": ["a", "b"],
        "edges": [{"src": "b", "dst": "a", "reason": "declared backwards"}],
    })
    assert "inversion" in m.check_edge("b", "a")


# -- the production tree's graph --------------------------------------------

def test_production_graph_contains_known_edges_and_no_cycles():
    edges = static_edges([str(REPO / "ballista_tpu")])
    for e in (
        ("scheduler.kv.lock", "scheduler.state._tenant_mu"),
        ("scheduler.kv.lock", "scheduler.server._push_mu"),
        ("scheduler.kv.lock", "scheduler.server._status_mu"),
        ("scheduler.kv.lock", "ops.costmodel._lock"),
        ("ops.stage._prepare_lock", "ops.runtime._res_lock"),
        ("ops.kernels._stage_cache_lock", "ops.runtime._res_lock"),
    ):
        assert e in edges, f"expected production edge {e} missing"
    m = Manifest.load()
    # every production edge declared + forward; no cycles (ex plan pairs)
    g = LockGraph()
    for s, d in edges:
        if not m.plan_pair(s, d):
            g.add(_site(s, d))
            assert m.check_edge(s, d) is None, (s, d, m.check_edge(s, d))
    assert g.cycles() == []


# -- atomicity ---------------------------------------------------------------

def test_atomicity_fixture_flagged():
    findings = [
        f for f in analyze_file(str(FIXTURES / "atomicity_bad.py"))
        if f.rule == RULE
    ]
    assert len(findings) == 1
    assert "check-then-act across a release" in findings[0].message


def test_atomicity_good_patterns_clean():
    """Double-checked insert, kill-on-fresh-reassignment, and the
    atomicity-ok annotation are all clean (lockorder_good.py)."""
    assert analyze_file(str(FIXTURES / "lockorder_good.py")) == []


def test_atomicity_ok_annotation_required(tmp_path):
    """Removing the annotation from the good fixture's reviewed
    check-then-act makes it a finding (the annotation is load-bearing)."""
    src = (FIXTURES / "lockorder_good.py").read_text().replace(
        "    # atomicity-ok: best-effort estimate; last writer wins by design\n",
        "",
    )
    p = tmp_path / "stripped.py"
    p.write_text(src.replace("path=ballista_tpu/ops/lockorder_good.py",
                             "path=ballista_tpu/ops/lockorder_good.py"))
    findings = [f for f in analyze_file(str(p)) if f.rule == RULE]
    assert any("check-then-act" in f.message for f in findings)


# -- dynamic witness ---------------------------------------------------------

@pytest.fixture
def witness():
    locks.reset_witness()
    locks.enable_witness()
    yield locks
    locks.disable_witness()
    locks.reset_witness()


def test_witness_records_edges(witness):
    a = locks.make_lock("scheduler.kv.lock")
    b = locks.make_lock("scheduler.server._push_mu")
    with a:
        with b:
            pass
    assert witness.witness_edges() == {
        ("scheduler.kv.lock", "scheduler.server._push_mu"): 1
    }
    assert witness.witness_violations() == []


def test_witness_asserts_on_declared_order_inversion(witness):
    a = locks.make_lock("scheduler.kv.lock")  # rank 0
    b = locks.make_lock("scheduler.server._push_mu")  # rank 1
    with pytest.raises(locks.LockOrderViolation) as ei:
        with b:
            with a:
                pass
    msg = str(ei.value)
    assert "inversion" in msg
    # both stacks attached, as the ISSUE demands
    assert "acquired at:" in msg and msg.count("File ") >= 2
    assert any(
        v["kind"] == "order_inversion" for v in witness.witness_violations()
    )


def test_witness_asserts_same_object_self_deadlock(witness):
    a = locks.make_lock("ops.runtime._res_lock")
    with pytest.raises(locks.LockOrderViolation, match="deadlocks now"):
        with a:
            with a:
                pass


def test_witness_allows_rlock_reentry_and_plan_tree_nesting(witness):
    r = locks.make_rlock("scheduler.kv.lock")
    with r:
        with r:
            pass
    j1 = locks.make_lock("physical.join._build_lock")
    j2 = locks.make_lock("physical.join._build_lock")
    with j1:
        with j2:  # distinct instances of a plan-tree class: legal
            pass
    assert not witness.witness_violations()


def test_witness_threads_have_independent_stacks(witness):
    a = locks.make_lock("scheduler.kv.lock")
    b = locks.make_lock("scheduler.server._push_mu")
    errs = []

    def other():
        try:
            with b:  # bare acquisition in another thread: no edge
                pass
        except Exception as e:  # pragma: no cover
            errs.append(e)

    with a:
        t = threading.Thread(target=other)
        t.start()
        t.join()
    assert not errs
    assert ("scheduler.kv.lock", "scheduler.server._push_mu") \
        not in witness.witness_edges()


def test_witness_dump_and_replay(witness, tmp_path):
    a = locks.make_lock("scheduler.kv.lock")
    b = locks.make_lock("scheduler.server._push_mu")
    with a:
        with b:
            pass
    out = tmp_path / "witness.json"
    rec = witness.dump(str(out))
    loaded = lockgraph.load_witness(str(out))
    assert loaded == json.loads(json.dumps(rec))
    assert loaded["edges"][0]["src"] == "scheduler.kv.lock"
    assert loaded["edges"][0]["count"] == 1
    assert "held_stack" in loaded["edges"][0]


def test_witness_disabled_is_transparent():
    locks.reset_witness()
    assert not locks.witness_enabled()
    a = locks.make_lock("ops.runtime._res_lock")
    with a:
        with a if False else locks.make_lock("utils.tracing._mu"):
            pass
    assert locks.witness_edges() == {}
    assert a.acquire(blocking=False)
    a.release()


# -- witness-vs-static diff --------------------------------------------------

def test_diff_witness_missed_and_stale():
    manifest = Manifest({
        "order": ["a", "b", "c"],
        "edges": [
            {"src": "a", "dst": "b", "reason": "live"},
            {"src": "a", "dst": "c", "reason": "stale declaration"},
        ],
    })
    witness = {
        "edges": [
            {"src": "a", "dst": "b", "count": 3},
            {"src": "b", "dst": "c", "count": 1},  # analyzer missed this
        ],
        "violations": [],
    }
    report = diff_witness(witness, {("a", "b")}, manifest)
    assert report["missed"] == [("b", "c")]
    assert ("a", "c") in report["never_witnessed"]
    assert ("a", "b") not in report["never_witnessed"]


def test_diff_witness_plan_pairs_exempt_from_missed():
    manifest = Manifest({
        "order": [],
        "locks": {
            "p1": {"plan_tree": "x"},
            "p2": {"plan_tree": "x"},
        },
    })
    witness = {"edges": [{"src": "p1", "dst": "p2", "count": 1}],
               "violations": []}
    assert diff_witness(witness, set(), manifest)["missed"] == []


def test_check_witness_cli(tmp_path):
    """--check-witness: a runtime edge the static analyzer missed exits 1;
    a witness that is a subset of the static graph exits 0."""
    bogus = tmp_path / "bogus.json"
    bogus.write_text(json.dumps({
        "edges": [{"src": "utils.tracing._mu", "dst": "scheduler.kv.lock",
                   "count": 1}],
        "violations": [],
    }))
    proc = subprocess.run(
        [sys.executable, "-m", "dev.analysis", "--check-witness", str(bogus),
         "ballista_tpu"],
        cwd=str(REPO), capture_output=True, text=True,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "MISSED statically" in proc.stdout

    ok = tmp_path / "ok.json"
    ok.write_text(json.dumps({
        "edges": [{"src": "scheduler.kv.lock",
                   "dst": "scheduler.state._tenant_mu", "count": 5}],
        "violations": [],
    }))
    proc = subprocess.run(
        [sys.executable, "-m", "dev.analysis", "--check-witness", str(ok),
         "ballista_tpu", "--json"],
        cwd=str(REPO), capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout)
    assert out["ok"] and out["missed"] == []


def test_check_witness_cli_fails_on_recorded_violation(tmp_path):
    w = tmp_path / "v.json"
    w.write_text(json.dumps({
        "edges": [],
        "violations": [{"kind": "order_inversion", "src": "a", "dst": "b"}],
    }))
    proc = subprocess.run(
        [sys.executable, "-m", "dev.analysis", "--check-witness", str(w),
         "ballista_tpu"],
        cwd=str(REPO), capture_output=True, text=True,
    )
    assert proc.returncode == 1
    assert "RUNTIME VIOLATION" in proc.stdout


def test_check_witness_cli_merges_multiple_dumps(tmp_path):
    """ISSUE 18 satellite: witness CI lanes fork worker processes that
    each dump <OUT>.<pid>; --check-witness accepts the flag repeatedly
    and merges the edge sets before the diff — a missed edge in ANY dump
    fails, duplicate edges collapse to one merged runtime edge."""
    known = {"src": "scheduler.kv.lock",
             "dst": "scheduler.state._tenant_mu", "count": 2}
    a = tmp_path / "w.json.101"
    a.write_text(json.dumps({"edges": [known], "violations": []}))
    b = tmp_path / "w.json.102"
    b.write_text(json.dumps({
        "edges": [dict(known, count=3),
                  {"src": "utils.tracing._mu", "dst": "scheduler.kv.lock",
                   "count": 1}],
        "violations": [],
    }))
    proc = subprocess.run(
        [sys.executable, "-m", "dev.analysis",
         "--check-witness", str(a), "--check-witness", str(b),
         "ballista_tpu", "--json"],
        cwd=str(REPO), capture_output=True, text=True,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    out = json.loads(proc.stdout)
    assert out["witness_files"] == 2
    assert out["missed"] == [["utils.tracing._mu", "scheduler.kv.lock"]]
    # the duplicated known edge merged into ONE runtime edge
    assert out["runtime_edges"] == 2

    # both dumps subsets of the static graph: the merged check passes
    b.write_text(json.dumps({"edges": [dict(known, count=3)],
                             "violations": []}))
    proc = subprocess.run(
        [sys.executable, "-m", "dev.analysis",
         "--check-witness", str(a), "--check-witness", str(b),
         "ballista_tpu", "--json"],
        cwd=str(REPO), capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout)
    assert out["ok"] and out["runtime_edges"] == 1


def test_env_armed_witness_dump_is_pid_suffixed(tmp_path):
    """Env-armed processes dump to <OUT>.<pid>, never <OUT> itself —
    concurrent workers inheriting one BALLISTA_LOCK_WITNESS_OUT must not
    clobber each other's atexit os.replace."""
    import os

    out = tmp_path / "w.json"
    code = (
        "from ballista_tpu.utils import locks\n"
        "a = locks.make_lock('scheduler.kv.lock')\n"
        "b = locks.make_lock('scheduler.state._tenant_mu')\n"
        "with a:\n"
        "    with b:\n"
        "        pass\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=str(REPO), capture_output=True, text=True,
        env=dict(os.environ, BALLISTA_LOCK_WITNESS="1",
                 BALLISTA_LOCK_WITNESS_OUT=str(out),
                 PYTHONPATH=str(REPO)),
    )
    assert proc.returncode == 0, proc.stderr
    assert not out.exists()
    dumps = list(tmp_path.glob("w.json.*"))
    assert len(dumps) == 1, dumps
    rec = lockgraph.load_witness(str(dumps[0]))
    assert {(e["src"], e["dst"]) for e in rec["edges"]} == {
        ("scheduler.kv.lock", "scheduler.state._tenant_mu")
    }


# -- parallel analysis (--jobs) ---------------------------------------------

def test_jobs_parallel_matches_serial_and_caches(tmp_path):
    work = tmp_path / "pkg" / "ballista_tpu" / "ops"
    work.mkdir(parents=True)
    import shutil

    for name in ("lockorder_bad.py", "atomicity_bad.py", "readback_bad.py",
                 "lockorder_good.py"):
        shutil.copy(FIXTURES / name, work / name)
    c1, c2 = tmp_path / "c1.json", tmp_path / "c2.json"
    serial, s_stats = run_paths([str(work)], cache_path=str(c1), jobs=1)
    parallel, p_stats = run_paths([str(work)], cache_path=str(c2), jobs=3)
    assert [f.to_dict() for f in serial] == [f.to_dict() for f in parallel]
    assert s_stats["files"] == p_stats["files"] == 4
    assert p_stats["cache_hits"] == 0
    # warm second parallel run: per-file results all served from cache,
    # global lock-order findings recomputed identically
    warm, w_stats = run_paths([str(work)], cache_path=str(c2), jobs=3)
    assert w_stats["cache_hits"] == 4
    assert [f.to_dict() for f in warm] == [f.to_dict() for f in parallel]


def test_jobs_cli_flag(tmp_path):
    proc = subprocess.run(
        [sys.executable, "-m", "dev.analysis", "ballista_tpu/utils",
         "--jobs", "2", "--no-cache"],
        cwd=str(REPO), capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# -- witness e2e smoke (the CI gate's in-suite twin) -------------------------

def test_witness_chaos_e2e_zero_violations_zero_missed(tmp_path):
    """ISSUE 14 acceptance: one seeded chaos e2e — executor death mid-run
    plus a scheduler restart on the same store — under
    ballista.debug.lock_witness=1. Hard asserts: ZERO declared-order
    violations recorded at runtime, and --check-witness semantics hold
    (zero runtime edges the static analyzer missed)."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    import ballista_tpu.scheduler.state as state_mod
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.executor.runtime import StandaloneCluster
    from ballista_tpu.utils.chaos import ChaosInjector

    # deterministic death seed (same scan as test_chaos.py: pure hashing)
    def find_death_seed():
        for seed in range(2000):
            inj = ChaosInjector(seed, rate=0.005, sites={"executor.death"})

            def death_poll(eid, horizon):
                for n in range(1, horizon):
                    if inj.should_inject("executor.death", f"{eid}/poll{n}"):
                        return n
                return None

            d0 = death_poll("local-0", 17)
            if d0 is not None and 4 <= d0 and death_poll("local-1", 400) is None:
                return seed
        pytest.fail("no death seed found")

    rng = np.random.default_rng(7)
    n = 5000
    t = pa.table({
        "g": pa.array([f"k{v}" for v in rng.integers(0, 5, n)]),
        "v": pa.array(rng.integers(0, 100, n), type=pa.int64()),
    })
    path = str(tmp_path / "t.parquet")
    pq.write_table(t, path)

    import time

    from ballista_tpu.ops.runtime import recovery_stats

    locks.reset_witness()
    locks.enable_witness()
    old_lease = state_mod.EXECUTOR_LEASE_SECS
    state_mod.EXECUTOR_LEASE_SECS = 1.0
    recovery_stats(reset=True)
    cluster = StandaloneCluster(n_executors=2, config=BallistaConfig({
        "ballista.debug.lock_witness": "1",
        "ballista.chaos.rate": "0.005",
        "ballista.chaos.seed": str(find_death_seed()),
        "ballista.chaos.sites": "executor.death",
        "ballista.rpc.retries": "20",
    }))
    cluster.scheduler_impl.lost_task_check_interval = 0.3
    try:
        ctx = BallistaContext(*cluster.scheduler_addr, settings={
            "ballista.cache.results": "false",
        })
        ctx.register_parquet("t", path)
        sql = "select g, sum(v) as s, count(*) as c from t group by g order by g"
        first = ctx.sql(sql).collect()
        # let the seeded death fire (local-0 dies within its first ~16
        # polls at 250ms), then restart the scheduler on the same store
        # (ISSUE 6 path) and re-run on the degraded cluster
        deadline = time.time() + 10
        while time.time() < deadline and not recovery_stats().get(
            "chaos_executor_death"
        ):
            time.sleep(0.1)
        cluster.restart_scheduler()
        second = ctx.sql(sql).collect()
        assert first.to_pydict() == second.to_pydict()
        ctx.close()
    finally:
        state_mod.EXECUTOR_LEASE_SECS = old_lease
        cluster.shutdown()
        locks.disable_witness()

    stats = recovery_stats(reset=True)
    assert stats.get("chaos_executor_death", 0) >= 1, stats
    assert stats.get("scheduler_restart", 0) >= 1, stats
    violations = locks.witness_violations()
    assert violations == [], violations
    out = tmp_path / "witness.json"
    witness_rec = locks.dump(str(out))
    locks.reset_witness()
    assert witness_rec["edges"], "witness saw no edges — not armed?"
    edges = static_edges([str(REPO / "ballista_tpu")])
    report = diff_witness(witness_rec, edges, Manifest.load())
    assert report["missed"] == [], (
        "runtime edges the static analyzer missed: "
        f"{report['missed']}\n(add the call-resolution or a may-acquire "
        "annotation; the witness caught an analyzer gap)"
    )


def test_witness_rlock_reentry_under_intermediate_lock(witness):
    """Review regression: re-entering an already-held REENTRANT lock after
    acquiring an intermediate lock (kv.lock -> counter lock -> kv.get, the
    canonical scheduler shape) can never block — it must not record a
    backwards edge or raise, whatever the declared ranks say."""
    kv = locks.make_rlock("scheduler.kv.lock")  # rank 0
    counter = locks.make_lock("ops.costmodel._lock")  # ranked far below
    with kv:
        with counter:
            with kv:  # legal re-entry, not an inversion
                pass
    assert witness.witness_violations() == []
    assert ("ops.costmodel._lock", "scheduler.kv.lock") \
        not in witness.witness_edges()


def test_static_rlock_reentry_under_intermediate_lock():
    """The static mirror of the same review regression: a nested re-entry
    of a held reentrant lock (direct `with`, or via a callee like kv.get)
    must not derive edges from the intermediate locks."""
    graph, _ = _graph_of({"ballista_tpu/scheduler/m.py": """
        from ballista_tpu.utils.locks import make_lock, make_rlock
        _kv_mu = make_rlock("scheduler.m._kv_mu")
        _c_lock = make_lock("scheduler.m._c_lock")
        def reenter_direct(self):
            with _kv_mu:
                with _c_lock:
                    with _kv_mu:
                        pass
        def kv_get(self):
            with _kv_mu:
                pass
        def reenter_via_call(self):
            with _kv_mu:
                with _c_lock:
                    kv_get(self)
    """})
    assert ("scheduler.m._c_lock", "scheduler.m._kv_mu") \
        not in graph.edge_set()
    assert ("scheduler.m._kv_mu", "scheduler.m._c_lock") in graph.edge_set()

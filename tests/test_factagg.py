"""Fact-side aggregation pushdown (ops/factagg.py): Aggregate over a PK-FK
join runs as host-dim + device fact partials + (optional) device top-k."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from ballista_tpu.config import BallistaConfig
from ballista_tpu.engine import ExecutionContext
from ballista_tpu.ops import kernels


@pytest.fixture
def star(tmp_path):
    """Fact table (20k rows, 3k distinct keys) + dim table (unique key)."""
    rng = np.random.default_rng(5)
    nf, nk = 20_000, 3000
    fact = pa.table(
        {
            "fk": pa.array(rng.integers(0, nk, nf), type=pa.int64()),
            "amount": pa.array(np.round(rng.uniform(1, 500, nf), 2)),
            "disc": pa.array(np.round(rng.uniform(0, 0.1, nf), 3)),
            "flag": pa.array(rng.integers(0, 2, nf), type=pa.int64()),
        }
    )
    dim = pa.table(
        {
            "dk": pa.array(np.arange(nk), type=pa.int64()),
            "attr": pa.array([f"grp-{i % 37}" for i in range(nk)]),
            "region": pa.array([f"r{i % 5}" for i in range(nk)]),
        }
    )
    pq.write_table(fact, str(tmp_path / "fact.parquet"))
    pq.write_table(dim, str(tmp_path / "dim.parquet"))
    return tmp_path


def _ctx(backend, star):
    ctx = ExecutionContext(
        BallistaConfig({"ballista.executor.backend": backend})
    )
    ctx.register_parquet("fact", str(star / "fact.parquet"))
    ctx.register_parquet("dim", str(star / "dim.parquet"))
    return ctx


Q_TOPK = """
    select fk, sum(amount * (1 - disc)) as rev, attr
    from dim, fact
    where dk = fk and flag = 1
    group by fk, attr
    order by rev desc
    limit 15
"""

Q_FULL = """
    select fk, sum(amount) as s, count(amount) as c, avg(amount) as a, attr
    from dim, fact
    where dk = fk
    group by fk, attr
    order by fk
"""


def _factagg_stages():
    from ballista_tpu.ops.factagg import FactAggregateStage

    return [
        s for s in kernels._stage_cache.values()
        if isinstance(s, FactAggregateStage)
    ]


def test_topk_pushdown_matches_host(star):
    kernels._stage_cache.clear()
    t = _ctx("tpu", star).sql(Q_TOPK).collect()
    h = _ctx("host", star).sql(Q_TOPK).collect()
    assert t.column("fk").to_pylist() == h.column("fk").to_pylist()
    assert t.column("attr").to_pylist() == h.column("attr").to_pylist()
    np.testing.assert_allclose(
        t.column("rev").to_numpy(), h.column("rev").to_numpy(), rtol=1e-4
    )
    stages = _factagg_stages()
    assert stages and stages[0].topk is not None, "top-k epilogue not engaged"


def test_full_select_matches_host(star):
    kernels._stage_cache.clear()
    t = _ctx("tpu", star).sql(Q_FULL).collect()
    h = _ctx("host", star).sql(Q_FULL).collect()
    assert t.num_rows == h.num_rows  # keys present in fact (~3000)
    assert t.num_rows > 2900
    assert t.column("fk").to_pylist() == h.column("fk").to_pylist()
    assert t.column("attr").to_pylist() == h.column("attr").to_pylist()
    assert t.column("c").to_pylist() == h.column("c").to_pylist()
    np.testing.assert_allclose(
        t.column("s").to_numpy(), h.column("s").to_numpy(), rtol=1e-4, atol=1e-3
    )
    np.testing.assert_allclose(
        t.column("a").to_numpy(), h.column("a").to_numpy(), rtol=1e-4, atol=1e-4
    )
    stages = _factagg_stages()
    assert stages and stages[0].topk is None  # member-select path


def test_duplicate_dim_keys_fall_back_to_host(star, tmp_path):
    """A dim side with duplicate join keys multiplies fact rows; the
    pushdown must decline and the host join must produce the answer."""
    rng = np.random.default_rng(6)
    dim2 = pa.table(
        {
            "dk": pa.array(np.concatenate([np.arange(3000), [0, 1, 2]]),
                           type=pa.int64()),
            "attr": pa.array([f"a{i}" for i in range(3003)]),
        }
    )
    pq.write_table(dim2, str(tmp_path / "dim2.parquet"))
    sql = """
        select fk, sum(amount) as s, attr from dim2, fact
        where dk = fk group by fk, attr order by fk, attr
    """
    outs = {}
    for backend in ("tpu", "host"):
        ctx = _ctx(backend, star)
        ctx.register_parquet("dim2", str(tmp_path / "dim2.parquet"))
        outs[backend] = ctx.sql(sql).collect()
    assert outs["tpu"].column("fk").to_pylist() == outs["host"].column("fk").to_pylist()
    np.testing.assert_allclose(
        outs["tpu"].column("s").to_numpy(), outs["host"].column("s").to_numpy(),
        rtol=1e-4, atol=1e-3,
    )


def test_no_match_keys_empty_result(star):
    sql = """
        select fk, sum(amount) as s from dim, fact
        where dk = fk and dk > 100000 group by fk
    """
    t = _ctx("tpu", star).sql(sql).collect()
    assert t.num_rows == 0


def test_topk_over_integer_sum(star):
    """ORDER BY SUM(int_col) LIMIT k: the device score must decode BOTH
    packed halves — ranking by the hi half alone collapses sums below 65536
    into ties (review regression)."""
    kernels._stage_cache.clear()
    sql = """
        select fk, sum(flag) as nf from dim, fact
        where dk = fk group by fk order by nf desc limit 10
    """
    t = _ctx("tpu", star).sql(sql).collect()
    h = _ctx("host", star).sql(sql).collect()
    assert t.column("nf").to_pylist() == h.column("nf").to_pylist()
    stages = _factagg_stages()
    assert stages and stages[0].topk is not None


def test_nested_dim_joins_group_by_dim_only(star, tmp_path):
    """q10 shape: the fact is nested under TWO dim joins and the group keys
    are all dim attributes (no fact key) — many fact keys fold into one
    output group, so factagg's per-key top-k must never rank it. The ladder
    now prefers the mapped rewrite here when ITS fused epilogue is live
    (it groups directly by the output keys, so the O(limit) readback is
    sound); either way the answer must match the host."""
    rng = np.random.default_rng(9)
    # dimA: dk -> ck (FK into dimB); dimB: ck -> cattr. group by cattr only.
    dimA = pa.table(
        {
            "dk": pa.array(np.arange(3000), type=pa.int64()),
            "ck": pa.array(rng.integers(0, 50, 3000), type=pa.int64()),
        }
    )
    dimB = pa.table(
        {
            "ck2": pa.array(np.arange(50), type=pa.int64()),
            "cattr": pa.array([f"c{i}" for i in range(50)]),
        }
    )
    pq.write_table(dimA, str(tmp_path / "dimA.parquet"))
    pq.write_table(dimB, str(tmp_path / "dimB.parquet"))
    sql = """
        select cattr, sum(amount) as s, count(*) as n
        from dimB, dimA, fact
        where ck2 = ck and dk = fk
        group by cattr
        order by s desc
        limit 12
    """
    kernels._stage_cache.clear()
    outs = {}
    for backend in ("tpu", "host"):
        ctx = _ctx(backend, star)
        ctx.register_parquet("dimA", str(tmp_path / "dimA.parquet"))
        ctx.register_parquet("dimB", str(tmp_path / "dimB.parquet"))
        outs[backend] = ctx.sql(sql).collect()
    t, h = outs["tpu"], outs["host"]
    np.testing.assert_allclose(
        t.column("s").to_numpy(), h.column("s").to_numpy(), rtol=1e-4
    )
    assert t.column("n").to_pylist() == h.column("n").to_pylist()
    assert t.column("cattr").to_pylist() == h.column("cattr").to_pylist()
    from ballista_tpu.ops.factagg import FactAggregateStage

    stages = [s for s in kernels._stage_cache.values() if s]
    assert stages, "device path did not engage"
    if isinstance(stages[0], FactAggregateStage):
        # factagg served it: per-key top-k must be OFF (dim-only grouping
        # would rank per-fact-key partials, the wrong quantity)
        assert stages[0].topk is None
    else:
        # the mapped rewrite won the ladder precisely because its fused
        # top-k ranks the OUTPUT groups
        assert stages[0].topk is not None


def test_planner_annotates_topk(star):
    ctx = _ctx("host", star)
    df = ctx.sql(Q_TOPK)
    plan = ctx.create_physical_plan(df.logical_plan())
    from ballista_tpu.physical.aggregate import HashAggregateExec

    def find(node):
        if isinstance(node, HashAggregateExec):
            return node
        for c in node.children():
            r = find(c)
            if r is not None:
                return r
        return None

    agg = find(plan)
    assert agg is not None
    tk = getattr(agg, "_topk_pushdown", None)
    assert tk == {
        "agg_index": 0, "descending": True, "k": 15, "strict": False,
        # multi-key extension: the resolved sort-key prefix and whether it
        # covers the whole ORDER BY (ops/stage.py's fused epilogue)
        "keys": [{"agg_index": 0, "descending": True}], "covered": True,
    }


def test_topk_int_sum_f32_collapse_boundary(tmp_path):
    """Integer SUM scores rank as f32 on device; above 2^24 distinct sums
    collapse into false ties (ADVICE r2). A collapse run spanning the
    candidate-pool boundary must fall back to the host plan, not silently
    return a smaller true sum."""
    import pyarrow.parquet as pq

    base = 1 << 25  # f32 ulp here is 4: base and base+1 collapse
    G = 4000
    sums = np.full(G, base, dtype=np.int64)
    sums[:5] = base + 1000 * (np.arange(5) + 1)  # distinct in f32
    # true 6th-largest f32-ties the base crowd; its HIGH index keeps it out
    # of the (index-stable) device top-k unless the tie check fires
    sums[G - 1] = base + 1
    rng = np.random.default_rng(0)
    fact = pa.table(
        {
            "fk": pa.array(np.arange(G), type=pa.int64()),
            "amount": pa.array(sums, type=pa.int64()),
            # incompressible filler so the fact file outweighs the dim file
            # (fact selection picks the largest scan chain)
            "pad1": pa.array(rng.uniform(0, 1, G)),
            "pad2": pa.array(rng.uniform(0, 1, G)),
            "pad3": pa.array(rng.uniform(0, 1, G)),
        }
    )
    dim = pa.table({"dk": pa.array(np.arange(G), type=pa.int64()),
                    "attr": pa.array([f"a{i}" for i in range(G)])})
    pq.write_table(fact, str(tmp_path / "fact.parquet"))
    pq.write_table(dim, str(tmp_path / "dim.parquet"))
    kernels._stage_cache.clear()
    sql = """
        select fk, sum(amount) as s, attr from dim, fact
        where dk = fk group by fk, attr order by s desc limit 10
    """
    # unit level: the device stage builds, runs the top-k path, and DECLINES
    # on the collapsed tie at the pool boundary instead of returning rows
    from ballista_tpu.ops.factagg import FactAggregateStage
    from ballista_tpu.ops.runtime import UnsupportedOnDevice
    from ballista_tpu.physical.aggregate import HashAggregateExec
    from ballista_tpu.physical.plan import TaskContext

    ctx = _ctx("tpu", tmp_path)
    cfg = ctx.config
    phys = ctx.create_physical_plan(ctx.sql(sql).logical_plan())

    def find_agg(n):
        if isinstance(n, HashAggregateExec):
            return n
        for c in n.children():
            r = find_agg(c)
            if r is not None:
                return r
        return None

    stage = FactAggregateStage(find_agg(phys))
    assert stage.topk is not None
    tctx = TaskContext(config=cfg, work_dir=str(tmp_path), job_id="t")
    with pytest.raises(UnsupportedOnDevice, match="tie at candidate boundary"):
        stage.run(0, tctx)

    # end to end the decline lands on the host plan: values match exactly.
    # The top-6 values are unique ints; equal-sum tail rows may tiebreak on
    # any key, so compare the VALUE lists.
    t = ctx.sql(sql).collect()
    h = _ctx("host", tmp_path).sql(sql).collect()
    assert t.column("s").to_pylist() == h.column("s").to_pylist()
    assert (base + 1) in t.column("s").to_pylist()


@pytest.fixture
def coupled_star(tmp_path):
    """q5-shaped schema: fact joins a secondary dim on a fact column, with
    an attribute coupling between primary and secondary dims."""
    rng = np.random.default_rng(11)
    n_orders, n_supp, nf = 900, 50, 24_000
    orders = pa.table(
        {
            "o_key": pa.array(np.arange(n_orders), type=pa.int64()),
            "o_flag": pa.array(rng.integers(0, 2, n_orders), type=pa.int64()),
            "c_nat": pa.array(rng.integers(0, 8, n_orders), type=pa.int64()),
        }
    )
    supplier = pa.table(
        {
            "s_key": pa.array(np.arange(n_supp), type=pa.int64()),
            "s_nat": pa.array(rng.integers(0, 8, n_supp), type=pa.int64()),
        }
    )
    nation = pa.table(
        {
            "nat_key": pa.array(np.arange(8), type=pa.int64()),
            "nat_name": pa.array([f"nation-{i}" for i in range(8)]),
            "nat_region": pa.array([i % 2 for i in range(8)], type=pa.int64()),
        }
    )
    fact = pa.table(
        {
            "f_okey": pa.array(rng.integers(0, n_orders, nf), type=pa.int64()),
            "f_skey": pa.array(rng.integers(0, n_supp, nf), type=pa.int64()),
            "amount": pa.array(np.round(rng.uniform(1, 100, nf), 2)),
        }
    )
    pq.write_table(fact, str(tmp_path / "fact.parquet"))
    pq.write_table(orders, str(tmp_path / "orders.parquet"))
    pq.write_table(supplier, str(tmp_path / "supplier.parquet"))
    pq.write_table(nation, str(tmp_path / "nation.parquet"))
    return tmp_path


Q_COUPLED = """
    select nat_name, sum(amount) as rev
    from orders, fact, supplier, nation
    where o_key = f_okey and f_skey = s_key and c_nat = s_nat
      and s_nat = nat_key and nat_region = 1 and o_flag = 1
    group by nat_name
    order by nat_name
"""


def _coupled_ctx(backend, star):
    ctx = ExecutionContext(BallistaConfig({"ballista.executor.backend": backend}))
    for t in ("fact", "orders", "supplier", "nation"):
        ctx.register_parquet(t, str(star / f"{t}.parquet"))
    return ctx


def test_coupled_secondary_dim_matches_host(coupled_star):
    """q5 shape: upper join keyed on a fact column with a primary<->secondary
    attribute coupling runs per-class on device (static mapped column)."""
    kernels._stage_cache.clear()
    t = _coupled_ctx("tpu", coupled_star).sql(Q_COUPLED).collect()
    h = _coupled_ctx("cpu", coupled_star).sql(Q_COUPLED).collect()
    stages = _factagg_stages()
    assert stages and stages[0].secondary is not None
    assert t.column("nat_name").to_pylist() == h.column("nat_name").to_pylist()
    np.testing.assert_allclose(
        np.array(t.column("rev").to_pylist()),
        np.array(h.column("rev").to_pylist()), rtol=1e-4,
    )


def test_coupled_secondary_impure_filter_falls_back(coupled_star):
    """A secondary-side filter that is NOT a pure function of the coupling
    attribute (here: on s_key itself) invalidates the static map — the
    stage must decline and the host fallback must stay correct."""
    sql = Q_COUPLED.replace("and o_flag = 1", "and o_flag = 1 and s_key < 25")
    kernels._stage_cache.clear()
    t = _coupled_ctx("tpu", coupled_star).sql(sql).collect()
    h = _coupled_ctx("cpu", coupled_star).sql(sql).collect()
    assert t.column("nat_name").to_pylist() == h.column("nat_name").to_pylist()
    np.testing.assert_allclose(
        np.array(t.column("rev").to_pylist()),
        np.array(h.column("rev").to_pylist()), rtol=1e-4,
    )


def test_semi_join_folds_into_membership(tmp_path):
    """q18 shape: a SEMI join above the fact's inner join folds whole into
    the dim-plan membership and the aggregation stays on device."""
    rng = np.random.default_rng(17)
    n_orders, nf = 600, 18_000
    orders = pa.table(
        {
            "o_key": pa.array(np.arange(n_orders), type=pa.int64()),
            "o_name": pa.array([f"o{i}" for i in range(n_orders)]),
        }
    )
    fact = pa.table(
        {
            "f_okey": pa.array(rng.integers(0, n_orders, nf), type=pa.int64()),
            "qty": pa.array(np.round(rng.uniform(1, 50, nf), 2)),
        }
    )
    pq.write_table(fact, str(tmp_path / "fact.parquet"))
    pq.write_table(orders, str(tmp_path / "orders.parquet"))
    sql = """
        select o_name, o_key, sum(qty) as s
        from orders, fact
        where o_key = f_okey
          and o_key in (select f_okey from fact group by f_okey
                        having sum(qty) > 800)
        group by o_name, o_key
        order by o_key
    """
    outs = {}
    for backend in ("tpu", "cpu"):
        kernels._stage_cache.clear()
        ctx = ExecutionContext(BallistaConfig({"ballista.executor.backend": backend}))
        ctx.register_parquet("fact", str(tmp_path / "fact.parquet"))
        ctx.register_parquet("orders", str(tmp_path / "orders.parquet"))
        outs[backend] = ctx.sql(sql).collect()
        if backend == "tpu":
            stages = _factagg_stages()
            assert stages, "device stage did not build for the semi fold"
    t, h = outs["tpu"], outs["cpu"]
    assert t.num_rows == h.num_rows > 0
    assert t.column("o_key").to_pylist() == h.column("o_key").to_pylist()
    np.testing.assert_allclose(
        np.array(t.column("s").to_pylist()),
        np.array(h.column("s").to_pylist()), rtol=1e-4,
    )


def test_fact_partitions_differ_from_driven_partitions(tmp_path):
    """A single-partition probe side with a multi-partition fact build side
    plans a SINGLE aggregate with NO merge — the fact stage must stripe
    every fact file into its one driven partition (reading only file p was
    a silent 1/N-of-the-data bug). Also covers the inverse shape (more
    probe partitions than fact files)."""
    import numpy as np
    import pyarrow.parquet as pq

    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.engine import ExecutionContext

    rng = np.random.default_rng(11)
    n = 40_000
    (tmp_path / "sales").mkdir()
    for p in range(4):
        t = pa.table({
            "cust": rng.integers(0, 500, n // 4),
            "amount": rng.uniform(1, 1000, n // 4),
        })
        pq.write_table(t, str(tmp_path / "sales" / f"part-{p}.parquet"))
    (tmp_path / "cust").mkdir()
    pq.write_table(
        pa.table({"c_id": np.arange(500)}), str(tmp_path / "cust" / "p0.parquet")
    )
    (tmp_path / "cust8").mkdir()
    for p in range(8):
        pq.write_table(
            pa.table({"c_id": np.arange(500)}).slice(p * 63, 63),
            str(tmp_path / "cust8" / f"part-{p}.parquet"),
        )

    full = pq.read_table(str(tmp_path / "sales")).to_pandas()
    want = full.groupby("cust").amount.sum().sort_index()
    topw = full.groupby("cust").amount.sum().nlargest(5)

    from ballista_tpu.ops import kernels, runtime
    from ballista_tpu.ops.factagg import FactAggregateStage

    kernels._stage_cache.clear()
    kernels._stage_cache_pins.clear()
    kernels._stage_latest.clear()
    runtime.reset_residency()
    for dim, probe_parts in (("cust", 1), ("cust8", 8)):
        for backend in ("cpu", "tpu"):
            ctx = ExecutionContext(
                BallistaConfig({"ballista.executor.backend": backend})
            )
            ctx.register_parquet("sales", str(tmp_path / "sales"))
            ctx.register_parquet(dim, str(tmp_path / dim))
            out = (
                ctx.sql(
                    f"select cust, sum(amount) as rev from sales, {dim} "
                    "where c_id = cust group by cust"
                )
                .collect().to_pandas().set_index("cust").rev.sort_index()
            )
            np.testing.assert_allclose(
                out.to_numpy(), want.to_numpy(), rtol=1e-4,
                err_msg=f"{backend}/{dim}",
            )
            top = ctx.sql(
                f"select cust, sum(amount) as rev from sales, {dim} "
                "where c_id = cust group by cust order by rev desc limit 5"
            ).collect().to_pandas()
            assert list(top.cust) == list(topw.index), (backend, dim)
    # the device fact-agg path must have RUN with striped fact reads (a
    # silent host fallback would also produce matching results)
    ran = [
        s for s in kernels._stage_cache.values()
        if isinstance(s, FactAggregateStage) and s._prepared
    ]
    assert ran, "device fact-agg stage did not run"
    assert any(s.inner.scan_stride is not None for s in ran)


def test_date_minmax_through_factagg(tmp_path):
    """MIN/MAX over a fact-side date32 column through the fact-agg pushdown
    (the partial assembly crashed casting double -> date32 before the
    shared state_column helper)."""
    rng = np.random.default_rng(8)
    nf, nk = 20_000, 2000
    fact = pa.table(
        {
            "fk": pa.array(rng.integers(0, nk, nf), type=pa.int64()),
            "amount": pa.array(rng.uniform(1, 100, nf)),
            "ship": pa.array(
                rng.integers(8000, 12000, nf), type=pa.int32()
            ).cast(pa.date32()),
        }
    )
    dim = pa.table(
        {
            "dk": pa.array(np.arange(nk), type=pa.int64()),
            "attr": pa.array([f"a{i % 11}" for i in range(nk)]),
        }
    )
    pq.write_table(fact, str(tmp_path / "fact.parquet"))
    pq.write_table(dim, str(tmp_path / "dim.parquet"))
    kernels._stage_cache.clear()
    res = {}
    for backend in ("tpu", "cpu"):
        ctx = ExecutionContext(
            BallistaConfig({"ballista.executor.backend": backend})
        )
        ctx.register_parquet("fact", str(tmp_path / "fact.parquet"))
        ctx.register_parquet("dim", str(tmp_path / "dim.parquet"))
        res[backend] = ctx.sql(
            "select fk, min(ship) as mn, max(ship) as mx, attr "
            "from dim, fact where dk = fk group by fk, attr order by fk"
        ).collect()
    assert _factagg_stages(), "fact-agg stage not engaged"
    t, c = res["tpu"], res["cpu"]
    assert t.column("mn").to_pylist() == c.column("mn").to_pylist()
    assert t.column("mx").to_pylist() == c.column("mx").to_pylist()

"""Fault tolerance & recovery: bounded task retries, lineage-based shuffle
recovery, lost-task rescheduling, scheduler restart resume (checkpointed
state), work-dir GC, transient-RPC backoff.

SURVEY §5 noted the reference has ~~"no retry"~~ — **no longer true of this
port** (ISSUE 5): a failed task is requeued up to
``ballista.shuffle.max_task_retries`` times with per-task executor
blacklisting, a dead executor's completed shuffle outputs are recomputed
via lineage (downstream consumers invalidated, fetch_failed statuses name
the lost location), and only retry exhaustion fails the job — with the full
attempt history in the error."""

import os
import time

import pyarrow as pa
import pytest

from ballista_tpu.proto import ballista_pb2 as pb
from ballista_tpu.scheduler.kv import MemoryBackend, SqliteBackend
from ballista_tpu.scheduler.state import SchedulerState


def _meta(i, port=1):
    return pb.ExecutorMetadata(id=i, host="h", port=port)


def _task(job, stage, part, status=None, executor="e1"):
    t = pb.TaskStatus()
    t.partition_id.job_id = job
    t.partition_id.stage_id = stage
    t.partition_id.partition_id = part
    if status == "running":
        t.running.executor_id = executor
    elif status == "completed":
        t.completed.executor_id = executor
        t.completed.path = "/x"
    return t


def test_reset_lost_tasks_on_dead_executor():
    s = SchedulerState(MemoryBackend(), "t")
    running = pb.JobStatus()
    running.running.SetInParent()
    s.save_job_metadata("j", running)
    # e1 alive, e2 dead (never registered)
    s.save_executor_metadata(_meta("e1"))
    s.save_task_status(_task("j", 1, 0, "running", "e1"))
    s.save_task_status(_task("j", 1, 1, "running", "e2"))
    s.save_task_status(_task("j", 1, 2, "completed", "e2"))
    n = s.reset_lost_tasks()
    assert n == 2
    statuses = {
        t.partition_id.partition_id: t.WhichOneof("status") for t in s.get_job_tasks("j")
    }
    assert statuses == {0: "running", 1: None, 2: None}


def test_reset_skips_finished_jobs():
    s = SchedulerState(MemoryBackend(), "t")
    done = pb.JobStatus()
    done.completed.SetInParent()
    s.save_job_metadata("j", done)
    s.save_task_status(_task("j", 1, 0, "completed", "gone"))
    assert s.reset_lost_tasks() == 0


def test_scheduler_restart_resumes_from_sqlite(tmp_path):
    """The de-facto checkpoint: job/task/stage state lives in the KV store,
    so a restarted scheduler on a durable backend retains it (ref SURVEY §5
    checkpoint/resume)."""
    db = str(tmp_path / "state.db")
    s1 = SchedulerState(SqliteBackend(db), "t")
    running = pb.JobStatus()
    running.running.SetInParent()
    s1.save_job_metadata("jobA", running)
    s1.save_task_status(_task("jobA", 1, 0, "completed"))
    s1.save_task_status(_task("jobA", 1, 1))
    del s1  # "crash"

    s2 = SchedulerState(SqliteBackend(db), "t")
    assert s2.get_job_metadata("jobA").WhichOneof("status") == "running"
    tasks = s2.get_job_tasks("jobA")
    assert len(tasks) == 2
    assert {t.WhichOneof("status") for t in tasks} == {"completed", None}


def test_end_to_end_recovery_after_executor_death(sales_table):
    """Kill an executor holding work mid-job; the job must still complete on
    the survivor (the reference would lose it)."""
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.executor.runtime import StandaloneCluster
    from ballista_tpu.scheduler.state import EXECUTOR_LEASE_SECS

    cluster = StandaloneCluster(n_executors=2)
    # shrink lease + check interval so death is detected quickly
    import ballista_tpu.scheduler.state as state_mod

    old_lease = state_mod.EXECUTOR_LEASE_SECS
    state_mod.EXECUTOR_LEASE_SECS = 1.0
    cluster.scheduler_impl.lost_task_check_interval = 0.5
    try:
        ctx = BallistaContext(*cluster.scheduler_addr)
        ctx.register_record_batches("sales", sales_table, n_partitions=4)
        # hard-stop one executor (its lease will lapse)
        victim = cluster.executors[0]
        victim.poll_loop.stop()
        time.sleep(1.5)  # lease expiry
        out = ctx.sql(
            "select region, sum(amount) as s from sales group by region order by region"
        ).collect()
        assert out.column("s").to_pylist() == [120.0, 40.0, 145.0]
        ctx.close()
    finally:
        state_mod.EXECUTOR_LEASE_SECS = old_lease
        cluster.shutdown()


# -- bounded retries + attempt history (ISSUE 5) ----------------------------

def _running_job(s, job="j"):
    running = pb.JobStatus()
    running.running.SetInParent()
    s.save_job_metadata(job, running)


def test_reset_preserves_attempt_history():
    """A lost-task reset consumes one retry: attempt increments and the
    history names the dead executor."""
    s = SchedulerState(MemoryBackend(), "t")
    _running_job(s)
    s.save_executor_metadata(_meta("e1"))
    s.save_task_status(_task("j", 1, 0, "completed", "gone"))
    assert s.reset_lost_tasks() == 1
    t = s.get_task_status("j", 1, 0)
    assert t.WhichOneof("status") is None and t.attempt == 1
    assert len(t.history) == 1 and t.history[0].executor_id == "gone"
    assert "shuffle output lost" in t.history[0].error


def test_reset_exhausted_fails_job_with_full_history():
    from ballista_tpu.config import BallistaConfig

    s = SchedulerState(
        MemoryBackend(), "t",
        config=BallistaConfig({"ballista.shuffle.max_task_retries": "1"}),
    )
    _running_job(s)
    s.save_executor_metadata(_meta("e1"))
    t = _task("j", 1, 0, "completed", "gone")
    t.attempt = 1
    h = t.history.add()
    h.attempt = 0
    h.executor_id = "gone"
    h.error = "earlier loss"
    s.save_task_status(t)
    assert s.reset_lost_tasks() == 0
    js = s.get_job_metadata("j")
    assert js.WhichOneof("status") == "failed"
    # every attempt is listed
    assert "attempt 0 on gone: earlier loss" in js.failed.error
    assert "attempt 1 on gone" in js.failed.error


def test_failed_task_requeues_then_exhausts_listing_every_attempt():
    """The retry fold end to end at the state level: N failures requeue,
    failure N+1 fails the job with all attempts in the error."""
    from ballista_tpu.config import BallistaConfig

    s = SchedulerState(
        MemoryBackend(), "t",
        config=BallistaConfig({"ballista.shuffle.max_task_retries": "2"}),
    )
    _running_job(s)
    for attempt, executor in enumerate(["e1", "e2", "e1"]):
        t = s.get_task_status("j", 1, 0) or _task("j", 1, 0)
        report = pb.TaskStatus()
        report.CopyFrom(t)
        report.failed.error = f"boom{attempt}"
        report.failed.executor_id = executor
        assert s.accept_task_status(report)
        s.synchronize_job_status("j")
        if attempt < 2:
            cur = s.get_task_status("j", 1, 0)
            assert cur.WhichOneof("status") is None
            assert cur.attempt == attempt + 1
            assert s.get_job_metadata("j").WhichOneof("status") == "running"
    js = s.get_job_metadata("j")
    assert js.WhichOneof("status") == "failed"
    for line in ("attempt 0 on e1: boom0", "attempt 1 on e2: boom1",
                 "attempt 2 on e1: boom2"):
        assert line in js.failed.error, js.failed.error


def test_stale_report_from_reset_attempt_is_dropped():
    s = SchedulerState(MemoryBackend(), "t")
    _running_job(s)
    requeued = _task("j", 1, 0)
    requeued.attempt = 2
    s.save_task_status(requeued)
    stale = _task("j", 1, 0, "completed", "e-old")
    stale.attempt = 1  # the attempt the scheduler already reset
    assert not s.accept_task_status(stale)
    assert s.get_task_status("j", 1, 0).WhichOneof("status") is None


def test_assignment_blacklists_last_failing_executor():
    """Attempt N+1 must not land on the executor that failed attempt N —
    unless it is the only one left alive."""
    from ballista_tpu.physical.basic import EmptyExec

    s = SchedulerState(MemoryBackend(), "t")
    _running_job(s)
    s.save_executor_metadata(_meta("e1", 1))
    s.save_executor_metadata(_meta("e2", 2))
    s.save_stage_plan("j", 1, EmptyExec(True, pa.schema([("a", pa.int64())])))
    t = _task("j", 1, 0)
    t.attempt = 1
    h = t.history.add()
    h.attempt = 0
    h.executor_id = "e1"
    h.error = "boom"
    s.save_task_status(t)
    assert s.assign_next_schedulable_task("e1") is None  # blacklisted
    got = s.assign_next_schedulable_task("e2")
    assert got is not None and got[0].running.executor_id == "e2"
    assert got[0].attempt == 1  # attempt rides the assignment

    # sole survivor: with e2 gone, e1 gets it anyway (progress over placement)
    s2 = SchedulerState(MemoryBackend(), "t")
    _running_job(s2)
    s2.save_executor_metadata(_meta("e1", 1))
    s2.save_stage_plan("j", 1, EmptyExec(True, pa.schema([("a", pa.int64())])))
    s2.save_task_status(t)
    got = s2.assign_next_schedulable_task("e1")
    assert got is not None and got[0].running.executor_id == "e1"


# -- lineage-based shuffle recovery (ISSUE 5) -------------------------------

def _two_stage_state(max_retries="3"):
    """Stage 1 (map, 2 partitions) -> stage 2 (reduce) via an
    UnresolvedShuffleExec, as the distributed planner lays jobs out."""
    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.distributed.stages import UnresolvedShuffleExec
    from ballista_tpu.physical.basic import EmptyExec

    s = SchedulerState(
        MemoryBackend(), "t",
        config=BallistaConfig({"ballista.shuffle.max_task_retries": max_retries}),
    )
    _running_job(s)
    schema = pa.schema([("a", pa.int64())])
    s.save_stage_plan("j", 1, EmptyExec(True, pa.schema([("a", pa.int64())])))
    s.save_stage_plan("j", 2, UnresolvedShuffleExec(1, schema, 2))
    return s


def test_lineage_completed_map_on_dead_executor_with_running_consumer():
    """Satellite regression (pre-fix-failing): a COMPLETED map task on a
    dead executor while a downstream reduce RUNS on a live executor. Before
    ISSUE 5 the reset put the map back to pending but left the running
    reduce bound to the dead location — its fetch failed, the failed status
    killed the job (reference behavior: in-flight work lost). Now BOTH are
    requeued with the loss recorded, and the job keeps running."""
    s = _two_stage_state()
    s.save_executor_metadata(_meta("e1"))  # alive; e2 never registered = dead
    s.save_task_status(_task("j", 1, 0, "completed", "e2"))  # lost output
    s.save_task_status(_task("j", 1, 1, "completed", "e1"))
    s.save_task_status(_task("j", 2, 0, "running", "e1"))  # live consumer
    n = s.reset_lost_tasks()
    assert n == 2  # the lost map task AND its running consumer
    mt = s.get_task_status("j", 1, 0)
    assert mt.WhichOneof("status") is None and mt.attempt == 1
    rt = s.get_task_status("j", 2, 0)
    assert rt.WhichOneof("status") is None and rt.attempt == 1
    assert "lost" in rt.history[0].error
    # the map output on the LIVE executor is untouched
    assert s.get_task_status("j", 1, 1).WhichOneof("status") == "completed"
    assert s.get_job_metadata("j").WhichOneof("status") == "running"


def test_fetch_failed_recomputes_only_the_lost_map_partition():
    """A reduce task reporting fetch_failed names the lost location; the
    scheduler requeues the reporter AND exactly that map partition."""
    s = _two_stage_state()
    s.save_executor_metadata(_meta("e1"))
    s.save_executor_metadata(_meta("e2", 2))
    s.save_task_status(_task("j", 1, 0, "completed", "e2"))
    s.save_task_status(_task("j", 1, 1, "completed", "e1"))
    report = _task("j", 2, 0)
    report.fetch_failed.error = "connection refused"
    report.fetch_failed.executor_id = "e1"
    report.fetch_failed.map_stage_id = 1
    report.fetch_failed.map_partition_id = 0
    report.fetch_failed.map_executor_id = "e2"
    report.fetch_failed.path = "/work/j/1/0"
    assert s.accept_task_status(report)
    s.synchronize_job_status("j")
    assert s.get_job_metadata("j").WhichOneof("status") == "running"
    # the reporter is requeued with the loss in its history
    rt = s.get_task_status("j", 2, 0)
    assert rt.WhichOneof("status") is None and rt.attempt == 1
    assert "fetch_failed" in rt.history[0].error
    # ONLY map partition 0 (the named one) is recomputed
    assert s.get_task_status("j", 1, 0).WhichOneof("status") is None
    assert s.get_task_status("j", 1, 0).attempt == 1
    assert s.get_task_status("j", 1, 1).WhichOneof("status") == "completed"


def test_orphaned_assignment_is_reconciled():
    """PollWork is retried and not idempotent: if the response carrying an
    assignment is lost, the task sits Running on an executor that never
    heard of it (lease stays fresh — reset_lost_tasks can't help). The
    executor's running_tasks echo lets the scheduler requeue it."""
    import ballista_tpu.scheduler.state as state_mod
    from ballista_tpu.physical.basic import EmptyExec

    s = SchedulerState(MemoryBackend(), "t")
    _running_job(s)
    s.save_executor_metadata(_meta("e1"))
    s.save_stage_plan("j", 1, EmptyExec(True, pa.schema([("a", pa.int64())])))
    s.save_task_status(_task("j", 1, 0))
    assert s.assign_next_schedulable_task("e1") is not None
    # within the grace period an empty echo is fine (the executor may not
    # have received/started the task yet)
    assert s.reconcile_running_tasks("e1", []) == 0
    assert s.get_task_status("j", 1, 0).WhichOneof("status") == "running"
    old = state_mod.ORPHANED_ASSIGNMENT_GRACE_SECS
    state_mod.ORPHANED_ASSIGNMENT_GRACE_SECS = 0.0
    try:
        assert s.reconcile_running_tasks("e1", []) == 1
    finally:
        state_mod.ORPHANED_ASSIGNMENT_GRACE_SECS = old
    t = s.get_task_status("j", 1, 0)
    assert t.WhichOneof("status") is None and t.attempt == 1
    assert "lost in transit" in t.history[0].error


def test_reconcile_keeps_confirmed_running_tasks():
    import ballista_tpu.scheduler.state as state_mod
    from ballista_tpu.physical.basic import EmptyExec

    s = SchedulerState(MemoryBackend(), "t")
    _running_job(s)
    s.save_executor_metadata(_meta("e1"))
    s.save_stage_plan("j", 1, EmptyExec(True, pa.schema([("a", pa.int64())])))
    s.save_task_status(_task("j", 1, 0))
    status, _plan = s.assign_next_schedulable_task("e1")
    old = state_mod.ORPHANED_ASSIGNMENT_GRACE_SECS
    state_mod.ORPHANED_ASSIGNMENT_GRACE_SECS = 0.0
    try:
        # a DIFFERENT executor's empty echo must not reclaim e1's task
        assert s.reconcile_running_tasks("e2", []) == 0
        assert s.get_task_status("j", 1, 0).WhichOneof("status") == "running"
        # the owner vouches for the task: nothing reclaimed, stays running
        assert s.reconcile_running_tasks("e1", [status.partition_id]) == 0
        assert s.get_task_status("j", 1, 0).WhichOneof("status") == "running"
    finally:
        state_mod.ORPHANED_ASSIGNMENT_GRACE_SECS = old


# -- transient RPC resilience (ISSUE 5) -------------------------------------

class _FakeGrpcError(Exception):
    def __init__(self, code, detail="go away"):
        self._code = code
        self._detail = detail

    def code(self):
        return self._code

    def details(self):
        return self._detail


def _client_with_stub(stub, retries=3):
    """SchedulerGrpcClient whose PollWork stub is replaced — no server."""
    import grpc

    from ballista_tpu.scheduler.rpc import SchedulerGrpcClient

    c = SchedulerGrpcClient("127.0.0.1", 1, channel=grpc.insecure_channel(
        "127.0.0.1:1"), retries=retries, backoff_s=0.0)
    # stub cache is keyed (endpoint_idx, method) since ISSUE 20; one
    # configured endpoint means every call resolves through index 0
    c._stub_cache[(0, "PollWork")] = stub
    c._stub_cache[(0, "GetFileMetadata")] = stub
    return c


def test_rpc_retries_unavailable_then_succeeds(monkeypatch):
    import grpc

    # grpc.RpcError is the catch target; fake must subclass it
    class Boom(grpc.RpcError, _FakeGrpcError):
        def __init__(self, code):
            _FakeGrpcError.__init__(self, code)

    calls = []

    def stub(params):
        calls.append(1)
        if len(calls) < 3:
            raise Boom(grpc.StatusCode.UNAVAILABLE)
        return pb.PollWorkResult()

    c = _client_with_stub(stub)
    assert c.poll_work(pb.PollWorkParams()) is not None
    assert len(calls) == 3


def test_rpc_retries_cancelled_goaway(monkeypatch):
    """ISSUE 11 regression: a scheduler crash/restart stops its gRPC
    server, which GOAWAYs in-flight unary calls as CANCELLED — the other
    went-away shape, retried like UNAVAILABLE (this client never cancels
    its own unary calls)."""
    import grpc

    class Boom(grpc.RpcError, _FakeGrpcError):
        def __init__(self, code):
            _FakeGrpcError.__init__(self, code)

    calls = []

    def stub(params):
        calls.append(1)
        if len(calls) < 2:
            raise Boom(grpc.StatusCode.CANCELLED)
        return pb.PollWorkResult()

    c = _client_with_stub(stub)
    assert c.poll_work(pb.PollWorkParams()) is not None
    assert len(calls) == 2


def test_rpc_does_not_retry_execution_errors():
    import grpc

    from ballista_tpu.errors import RpcError

    class Boom(grpc.RpcError, _FakeGrpcError):
        def __init__(self):
            _FakeGrpcError.__init__(self, grpc.StatusCode.UNKNOWN, "planner exploded")

    calls = []

    def stub(params):
        calls.append(1)
        raise Boom()

    c = _client_with_stub(stub)
    with pytest.raises(RpcError, match="planner exploded"):
        c.poll_work(pb.PollWorkParams())
    assert len(calls) == 1  # surfaced immediately


def test_rpc_retry_budget_exhausts():
    import grpc

    from ballista_tpu.errors import RpcError

    class Boom(grpc.RpcError, _FakeGrpcError):
        def __init__(self):
            _FakeGrpcError.__init__(self, grpc.StatusCode.UNAVAILABLE)

    calls = []

    def stub(params):
        calls.append(1)
        raise Boom()

    c = _client_with_stub(stub, retries=2)
    with pytest.raises(RpcError):
        c.poll_work(pb.PollWorkParams())
    assert len(calls) == 3  # 1 + 2 retries


def test_get_file_metadata_honors_throttle_hint():
    """Satellite: the scheduler's fail-fast 'too many concurrent metadata
    requests; retry' response is retried with backoff, not surfaced."""
    import grpc

    class Boom(grpc.RpcError, _FakeGrpcError):
        def __init__(self):
            _FakeGrpcError.__init__(
                self, grpc.StatusCode.UNKNOWN,
                "Exception calling application: GetFileMetadata: too many "
                "concurrent metadata requests; retry",
            )

    calls = []

    def stub(params):
        calls.append(1)
        if len(calls) < 3:
            raise Boom()
        return pb.GetFileMetadataResult(num_partitions=7)

    c = _client_with_stub(stub)
    out = c.get_file_metadata(pb.GetFileMetadataParams(path="x", file_type="parquet"))
    assert out.num_partitions == 7 and len(calls) == 3


# -- poll-loop slot handling (ISSUE 5 satellite: TOCTOU fix) ----------------

class _FakeScheduler:
    def __init__(self, tasks=None):
        self.tasks = list(tasks or [])
        self.polls = []

    def poll_work(self, params):
        self.polls.append(params)
        result = pb.PollWorkResult()
        if params.can_accept_task and self.tasks:
            result.task.CopyFrom(self.tasks.pop(0))
        return result


def _poll_loop(scheduler, tmp_path, concurrent_tasks=1):
    from ballista_tpu.executor.execution_loop import PollLoop

    meta = pb.ExecutorMetadata(id="e-test", host="h", port=1)
    return PollLoop(scheduler, meta, str(tmp_path),
                    concurrent_tasks=concurrent_tasks)


def test_poll_once_never_blocks_when_slots_are_full(tmp_path):
    """The TOCTOU fix: with every slot taken, poll_once must report
    can_accept_task=False and return immediately — the old probe/release +
    blocking re-acquire could hang the heartbeat thread here."""
    sched = _FakeScheduler()
    loop = _poll_loop(sched, tmp_path, concurrent_tasks=1)
    assert loop._available.acquire(blocking=False)  # occupy the only slot
    done = []

    def poller():
        loop.poll_once()
        done.append(True)

    import threading

    t = threading.Thread(target=poller, daemon=True)
    t.start()
    t.join(timeout=2.0)
    assert done, "poll_once blocked with all slots taken (heartbeat stall)"
    assert sched.polls[-1].can_accept_task is False


def test_poll_once_hands_held_slot_to_the_task(tmp_path):
    """The slot acquired by the probe is the SAME one the task runs under:
    after receiving a task, no slot remains (concurrent_tasks=1) and the
    next poll advertises can_accept_task=False until the task finishes."""
    task = pb.TaskDefinition()
    task.task_id.job_id = "j"
    task.task_id.stage_id = 1
    sched = _FakeScheduler(tasks=[task])
    loop = _poll_loop(sched, tmp_path, concurrent_tasks=1)
    gate = __import__("threading").Event()

    def fake_run(task, slot_held=True):
        gate.wait(5)
        loop._available.release()

    loop._run_task = fake_run
    assert loop.poll_once() is True
    assert sched.polls[-1].can_accept_task is True
    # slot is held by the (gated) task thread now, and the in-flight task
    # is echoed so the scheduler can reconcile lost assignments
    loop.poll_once()
    assert sched.polls[-1].can_accept_task is False
    assert [p.job_id for p in sched.polls[-1].running_tasks] == ["j"]
    gate.set()


def test_poll_failure_requeues_drained_statuses(tmp_path):
    """Statuses drained into a failing poll must survive to the next poll —
    losing them would wedge their job forever."""

    class FailingScheduler:
        def poll_work(self, params):
            raise RuntimeError("scheduler unreachable")

    loop = _poll_loop(FailingScheduler(), tmp_path)
    st = pb.TaskStatus()
    st.partition_id.job_id = "j"
    st.completed.executor_id = "e-test"
    loop._finished.put(st)
    with pytest.raises(RuntimeError):
        loop.poll_once()
    assert loop._finished.qsize() == 1  # requeued, not lost


# -- end-to-end lineage recovery (ISSUE 5 acceptance) -----------------------

def test_end_to_end_recovery_after_executor_death_with_lost_outputs(sales_table):
    """Executor killed AFTER its map stage completed: outputs lost while
    downstream reduces run. The job must still complete on the survivor via
    lineage recomputation (fetch_failed -> map recompute, lost-task resets),
    with nonzero recovery counters in the new bench fields."""
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.executor.runtime import StandaloneCluster
    from ballista_tpu.ops.runtime import recovery_stats
    from ballista_tpu.serde.logical import plan_to_proto
    import ballista_tpu.scheduler.state as state_mod

    cluster = StandaloneCluster(n_executors=2)
    old_lease = state_mod.EXECUTOR_LEASE_SECS
    state_mod.EXECUTOR_LEASE_SECS = 1.0
    cluster.scheduler_impl.lost_task_check_interval = 0.3
    recovery_stats(reset=True)
    try:
        ctx = BallistaContext(*cluster.scheduler_addr)
        ctx.register_record_batches("sales", sales_table, n_partitions=4)
        df = ctx.sql(
            "select region, sum(amount) as s from sales group by region order by region"
        )
        plan = df.logical_plan()
        params = pb.ExecuteQueryParams()
        params.logical_plan.CopyFrom(plan_to_proto(plan))
        for k, v in ctx.config.explicit_settings().items():
            params.settings.add(key=k, value=v)
        job_id = ctx._client.execute_query(params).job_id

        # wait for the FIRST stage (the maps) to fully complete
        state = cluster.scheduler_impl.state
        deadline = time.time() + 60
        stage1 = []
        while time.time() < deadline:
            tasks = state.get_job_tasks(job_id)
            if tasks:
                first = min(t.partition_id.stage_id for t in tasks)
                stage1 = [t for t in tasks if t.partition_id.stage_id == first]
                if stage1 and all(
                    t.WhichOneof("status") == "completed" for t in stage1
                ):
                    break
            time.sleep(0.02)
        else:
            pytest.fail("map stage did not complete in time")

        # kill an executor that holds completed map outputs — TOTALLY
        # (heartbeat AND data plane), so its outputs really are unreachable
        owners = {t.completed.executor_id for t in stage1}
        victim = next(ex for ex in cluster.executors if ex.id in owners)
        victim.stop()

        status = ctx._wait_for_job(job_id, timeout=120.0)
        tables = [
            ctx._fetch_partition(loc)
            for loc in status.completed.partition_location
        ]
        out = pa.concat_tables(tables).cast(plan.schema())
        assert out.column("s").to_pylist() == [120.0, 40.0, 145.0]

        stats = recovery_stats()
        recovered = (
            stats.get("fetch_failed", 0)
            + stats.get("map_recomputed", 0)
            + stats.get("lost_task_reset", 0)
            + stats.get("downstream_invalidated", 0)
        )
        assert recovered > 0, f"no recovery events recorded: {stats}"
        assert stats.get("task_retry", 0) > 0, stats
        ctx.close()
    finally:
        state_mod.EXECUTOR_LEASE_SECS = old_lease
        cluster.shutdown()


def test_completed_job_with_lost_result_partitions_restarts(sales_table):
    """PR 5 residue (ISSUE 6 satellite): a COMPLETED job whose result
    partitions died with their executor BEFORE the client fetched them was
    never restarted — reset_lost_tasks skips terminal jobs, so the client's
    fetch surfaced an RpcError (pre-fix this test fails exactly there).
    Now the client detects the loss at fetch time (ShuffleFetchError
    against the terminal job), reports it via ReportLostPartition, and the
    scheduler restarts the lost final-stage tasks through the normal
    lineage/retry machinery — the collect returns correct results."""
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.executor.runtime import StandaloneCluster
    from ballista_tpu.ops.runtime import recovery_stats
    import ballista_tpu.scheduler.state as state_mod

    cluster = StandaloneCluster(n_executors=2)
    old_lease = state_mod.EXECUTOR_LEASE_SECS
    state_mod.EXECUTOR_LEASE_SECS = 1.0
    cluster.scheduler_impl.lost_task_check_interval = 0.3
    recovery_stats(reset=True)
    try:
        ctx = BallistaContext(*cluster.scheduler_addr)
        ctx.register_record_batches("sales", sales_table, n_partitions=4)
        df = ctx.sql(
            "select region, sum(amount) as s from sales group by region order by region"
        )
        plan = df.logical_plan()
        job_id = ctx.submit(plan)
        status = ctx._wait_for_job(job_id, timeout=60.0)

        # kill ONE executor holding a result partition — totally (heartbeat
        # AND data plane) — BEFORE anything is fetched; the survivor must
        # recompute its partitions after the fetch-time report
        owners = [
            pl.executor_meta.id for pl in status.completed.partition_location
        ]
        assert owners, "completed job must expose result locations"
        victim = next(ex for ex in cluster.executors if ex.id in owners)
        victim.stop()

        out = ctx._collect_results(job_id, plan.schema(), timeout=120.0)
        assert out.column("s").to_pylist() == [120.0, 40.0, 145.0]

        stats = recovery_stats()
        assert stats.get("result_partition_restarted", 0) > 0, stats
        assert stats.get("completed_job_restarted", 0) > 0, stats
        assert stats.get("result_fetch_restarted", 0) > 0, stats
        ctx.close()
    finally:
        state_mod.EXECUTOR_LEASE_SECS = old_lease
        cluster.shutdown()


def test_restart_completed_job_declines_non_terminal_and_unknown():
    """ReportLostPartition is a no-op (restarted=False) for unknown/failed
    jobs and for executors that hold no final-stage output — the client
    re-raises its fetch error instead of looping. A RUNNING job with a
    completed final-stage task on the named executor DOES restart it
    (ISSUE 8: streaming clients fetch partial_location entries mid-job;
    without the requeue the dead location would be republished on every
    status fold) — and the job status stays running, no flip needed."""
    s = SchedulerState(MemoryBackend(), "t")
    assert s.restart_completed_job("nope", "e1") == 0
    failed = pb.JobStatus()
    failed.failed.error = "x"
    s.save_job_metadata("jf", failed)
    s.save_task_status(_task("jf", 1, 0, "completed", "e1"))
    assert s.restart_completed_job("jf", "e1") == 0  # terminal-failed
    _running_job(s, "jr")
    s.save_task_status(_task("jr", 1, 0, "completed", "e1"))
    assert s.restart_completed_job("jr", "e9") == 0  # e9 holds nothing
    assert s.restart_completed_job("jr", "e1") == 1  # running: requeued
    assert s.get_job_metadata("jr").WhichOneof("status") == "running"
    t = s.get_task_status("jr", 1, 0)
    assert t.WhichOneof("status") is None and t.attempt == 1
    done = pb.JobStatus()
    done.completed.SetInParent()
    s.save_job_metadata("jc", done)
    s.save_task_status(_task("jc", 1, 0, "completed", "e1"))
    s.save_task_status(_task("jc", 2, 0, "completed", "e1"))
    s.save_task_status(_task("jc", 2, 1, "completed", "e2"))
    assert s.restart_completed_job("jc", "e9") == 0  # e9 holds nothing
    assert s.get_job_metadata("jc").WhichOneof("status") == "completed"
    # e1's FINAL-stage task restarts (stage-1 output stays; lineage handles
    # it only if the re-run's fetch actually fails)
    assert s.restart_completed_job("jc", "e1") == 1
    assert s.get_job_metadata("jc").WhichOneof("status") == "running"
    t = s.get_task_status("jc", 2, 0)
    assert t.WhichOneof("status") is None and t.attempt == 1
    assert "result partition lost" in t.history[0].error
    # the untouched final task keeps its completed location
    assert s.get_task_status("jc", 2, 1).WhichOneof("status") == "completed"


def test_work_dir_gc(tmp_path):
    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.executor.execution_loop import PollLoop

    loop = PollLoop.__new__(PollLoop)  # no scheduler needed
    loop.work_dir = str(tmp_path)
    loop.config = BallistaConfig()  # the sweep reads the storage root too
    loop.shuffle_ttl_seconds = 0.1
    old = tmp_path / "old_job"
    old.mkdir()
    (old / "1").mkdir()
    time.sleep(0.2)
    fresh = tmp_path / "fresh_job"
    fresh.mkdir()
    removed = loop.gc_work_dir()
    assert removed == 1
    assert not old.exists() and fresh.exists()

"""Fault tolerance & recovery: lost-task rescheduling, scheduler restart
resume (checkpointed state, SURVEY §5), work-dir GC."""

import os
import time

import pyarrow as pa
import pytest

from ballista_tpu.proto import ballista_pb2 as pb
from ballista_tpu.scheduler.kv import MemoryBackend, SqliteBackend
from ballista_tpu.scheduler.state import SchedulerState


def _meta(i, port=1):
    return pb.ExecutorMetadata(id=i, host="h", port=port)


def _task(job, stage, part, status=None, executor="e1"):
    t = pb.TaskStatus()
    t.partition_id.job_id = job
    t.partition_id.stage_id = stage
    t.partition_id.partition_id = part
    if status == "running":
        t.running.executor_id = executor
    elif status == "completed":
        t.completed.executor_id = executor
        t.completed.path = "/x"
    return t


def test_reset_lost_tasks_on_dead_executor():
    s = SchedulerState(MemoryBackend(), "t")
    running = pb.JobStatus()
    running.running.SetInParent()
    s.save_job_metadata("j", running)
    # e1 alive, e2 dead (never registered)
    s.save_executor_metadata(_meta("e1"))
    s.save_task_status(_task("j", 1, 0, "running", "e1"))
    s.save_task_status(_task("j", 1, 1, "running", "e2"))
    s.save_task_status(_task("j", 1, 2, "completed", "e2"))
    n = s.reset_lost_tasks()
    assert n == 2
    statuses = {
        t.partition_id.partition_id: t.WhichOneof("status") for t in s.get_job_tasks("j")
    }
    assert statuses == {0: "running", 1: None, 2: None}


def test_reset_skips_finished_jobs():
    s = SchedulerState(MemoryBackend(), "t")
    done = pb.JobStatus()
    done.completed.SetInParent()
    s.save_job_metadata("j", done)
    s.save_task_status(_task("j", 1, 0, "completed", "gone"))
    assert s.reset_lost_tasks() == 0


def test_scheduler_restart_resumes_from_sqlite(tmp_path):
    """The de-facto checkpoint: job/task/stage state lives in the KV store,
    so a restarted scheduler on a durable backend retains it (ref SURVEY §5
    checkpoint/resume)."""
    db = str(tmp_path / "state.db")
    s1 = SchedulerState(SqliteBackend(db), "t")
    running = pb.JobStatus()
    running.running.SetInParent()
    s1.save_job_metadata("jobA", running)
    s1.save_task_status(_task("jobA", 1, 0, "completed"))
    s1.save_task_status(_task("jobA", 1, 1))
    del s1  # "crash"

    s2 = SchedulerState(SqliteBackend(db), "t")
    assert s2.get_job_metadata("jobA").WhichOneof("status") == "running"
    tasks = s2.get_job_tasks("jobA")
    assert len(tasks) == 2
    assert {t.WhichOneof("status") for t in tasks} == {"completed", None}


def test_end_to_end_recovery_after_executor_death(sales_table):
    """Kill an executor holding work mid-job; the job must still complete on
    the survivor (the reference would lose it)."""
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.executor.runtime import StandaloneCluster
    from ballista_tpu.scheduler.state import EXECUTOR_LEASE_SECS

    cluster = StandaloneCluster(n_executors=2)
    # shrink lease + check interval so death is detected quickly
    import ballista_tpu.scheduler.state as state_mod

    old_lease = state_mod.EXECUTOR_LEASE_SECS
    state_mod.EXECUTOR_LEASE_SECS = 1.0
    cluster.scheduler_impl.lost_task_check_interval = 0.5
    try:
        ctx = BallistaContext(*cluster.scheduler_addr)
        ctx.register_record_batches("sales", sales_table, n_partitions=4)
        # hard-stop one executor (its lease will lapse)
        victim = cluster.executors[0]
        victim.poll_loop.stop()
        time.sleep(1.5)  # lease expiry
        out = ctx.sql(
            "select region, sum(amount) as s from sales group by region order by region"
        ).collect()
        assert out.column("s").to_pylist() == [120.0, 40.0, 145.0]
        ctx.close()
    finally:
        state_mod.EXECUTOR_LEASE_SECS = old_lease
        cluster.shutdown()


def test_work_dir_gc(tmp_path):
    from ballista_tpu.executor.execution_loop import PollLoop

    loop = PollLoop.__new__(PollLoop)  # no scheduler needed
    loop.work_dir = str(tmp_path)
    loop.shuffle_ttl_seconds = 0.1
    old = tmp_path / "old_job"
    old.mkdir()
    (old / "1").mkdir()
    time.sleep(0.2)
    fresh = tmp_path / "fresh_job"
    fresh.mkdir()
    removed = loop.gc_work_dir()
    assert removed == 1
    assert not old.exists() and fresh.exists()

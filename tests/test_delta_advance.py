"""Result-cache advancement (ISSUE 19, tentpole B): appending a file to a
cached query's chunk set serves the new result by folding delta partials
into the cached aggregate state instead of recomputing from scratch.

Covers the full acceptance surface:

- end-to-end advancement on append: advance_hits >= 1 and the advanced
  result is BIT-IDENTICAL to a cold full run over the grown set;
- the advanced entry is self-contained (state inline in the KV value):
  a third submission is a plain cache hit with zero executor tasks, and
  the entry keeps serving across a scheduler restart on a durable store;
- cache.advance chaos (torn publish): the advancement declines and falls
  back to a FULL recompute — never a silent wrong answer;
- ineligible shapes (float sums are order-sensitive) decline loudly via
  the advance_declined counter and still return correct results.
"""

import os
import tempfile

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from ballista_tpu.client import BallistaContext
from ballista_tpu.config import BallistaConfig
from ballista_tpu.executor.runtime import StandaloneCluster
from ballista_tpu.ops.runtime import delta_stats, tenancy_stats
from ballista_tpu.scheduler.kv import SqliteBackend

# the canonical advancement-eligible shape: filter below the aggregate,
# order-insensitive members only (int sum / count / min), sort on the
# full group key so merged output lands in a deterministic row order
QUERY = (
    "select g, sum(v) as sv, count(*) as c, min(v) as mn "
    "from t where w > -5 group by g order by g"
)


def _write_part(d: str, i: int, n: int = 200) -> str:
    rng = np.random.default_rng(100 + i)
    path = os.path.join(d, f"part-{i}.parquet")
    pq.write_table(
        pa.table(
            {
                "g": pa.array(rng.integers(0, 7, n), type=pa.int64()),
                "v": pa.array(rng.integers(-50, 50, n), type=pa.int64()),
                "w": pa.array(rng.integers(-10, 10, n), type=pa.int64()),
                "f": pa.array(rng.random(n), type=pa.float64()),
            }
        ),
        path,
    )
    return path


@pytest.fixture()
def tdir():
    with tempfile.TemporaryDirectory() as d:
        _write_part(d, 0)
        _write_part(d, 1)
        yield d


def _cold_truth(cluster, d: str, query: str = QUERY) -> pa.Table:
    """Ground truth: a full run over the current file set with the result
    cache disabled, so nothing cached can leak into the reference."""
    ctx = BallistaContext(
        *cluster.scheduler_addr,
        settings={"ballista.cache.results": "false"},
    )
    try:
        ctx.register_parquet("t", d)
        return ctx.sql(query).collect()
    finally:
        ctx.close()


def _cached_jobs(state):
    out = []
    for k, _v in state.kv.get_prefix(state._key("jobs")):
        job = k.rsplit("/", 1)[1]
        js = state.get_job_metadata(job)
        if js.WhichOneof("status") == "completed" and js.completed.cached:
            out.append(job)
    return out


def test_advance_on_append_bit_identical(tdir):
    cluster = StandaloneCluster(n_executors=2)
    try:
        ctx = BallistaContext(
            *cluster.scheduler_addr,
            settings={"ballista.cache.advance": "true"},
        )
        ctx.register_parquet("t", tdir)
        delta_stats(reset=True)
        cold = ctx.sql(QUERY).collect()
        # grow the chunk set and re-register so the client re-discovers it
        _write_part(tdir, 2)
        ctx.register_parquet("t", tdir)
        advanced = ctx.sql(QUERY).collect()
        stats = delta_stats(reset=True)
        assert stats.get("advance_hits") == 1, stats
        # the acceptance bar: advanced result == cold full run, byte for byte
        truth = _cold_truth(cluster, tdir)
        assert advanced.equals(truth)
        assert not advanced.equals(cold)  # the append actually changed rows
        # the advanced entry is a first-class cache line: a third submission
        # is a plain hit served inline, with ZERO executor tasks
        tenancy_stats(reset=True)
        third = ctx.sql(QUERY).collect()
        assert third.equals(truth)
        assert tenancy_stats(reset=True).get("cache_hit") == 1
        st = cluster.scheduler_impl.state
        hits = _cached_jobs(st)
        assert hits and all(st.get_job_tasks(j) == [] for j in hits)
        ctx.close()
    finally:
        cluster.shutdown()


def test_advanced_entry_survives_scheduler_restart(tdir):
    """Advanced entries carry their state INLINE in the KV value, so they
    need no live executor and no scheduler memory: a restarted scheduler
    on the same durable store keeps serving the advanced result."""
    kv = SqliteBackend.temporary()
    cluster = StandaloneCluster(n_executors=1, kv=kv)
    try:
        ctx = BallistaContext(
            *cluster.scheduler_addr,
            settings={"ballista.cache.advance": "true"},
        )
        ctx.register_parquet("t", tdir)
        delta_stats(reset=True)
        ctx.sql(QUERY).collect()
        _write_part(tdir, 2)
        ctx.register_parquet("t", tdir)
        advanced = ctx.sql(QUERY).collect()
        assert delta_stats(reset=True).get("advance_hits") == 1
        cluster.restart_scheduler()
        tenancy_stats(reset=True)
        again = ctx.sql(QUERY).collect()
        assert again.equals(advanced)
        assert tenancy_stats(reset=True).get("cache_hit") == 1
        ctx.close()
    finally:
        cluster.shutdown()


def test_advance_chaos_torn_publish_falls_back(tdir):
    """cache.advance chaos fires BEFORE any KV write of the advanced
    entry: the advancement declines, the query falls back to a full
    recompute, and the answer is still bit-identical — a torn publish is
    a performance event, never a correctness event."""
    cfg = BallistaConfig(
        {
            "ballista.chaos.seed": "19",
            "ballista.chaos.rate": "1.0",
            "ballista.chaos.sites": "cache.advance",
        }
    )
    cluster = StandaloneCluster(n_executors=2, config=cfg)
    try:
        ctx = BallistaContext(
            *cluster.scheduler_addr,
            settings={"ballista.cache.advance": "true"},
        )
        ctx.register_parquet("t", tdir)
        delta_stats(reset=True)
        ctx.sql(QUERY).collect()
        _write_part(tdir, 2)
        ctx.register_parquet("t", tdir)
        result = ctx.sql(QUERY).collect()
        stats = delta_stats(reset=True)
        assert stats.get("advance_hits", 0) == 0, stats
        assert stats.get("advance_declined", 0) >= 1, stats
        assert result.equals(_cold_truth(cluster, tdir))
        ctx.close()
    finally:
        cluster.shutdown()


def test_float_sum_declines_to_full_recompute(tdir):
    """Float sums are order-sensitive (fp addition does not associate), so
    advancement cannot guarantee bit-identity: the fold spec declines,
    the decline is COUNTED (never silent), and the full recompute serves
    the correct rows."""
    q = "select g, sum(f) as sf, count(*) as c from t group by g order by g"
    cluster = StandaloneCluster(n_executors=2)
    try:
        ctx = BallistaContext(
            *cluster.scheduler_addr,
            settings={"ballista.cache.advance": "true"},
        )
        ctx.register_parquet("t", tdir)
        delta_stats(reset=True)
        ctx.sql(q).collect()
        _write_part(tdir, 2)
        ctx.register_parquet("t", tdir)
        result = ctx.sql(q).collect()
        stats = delta_stats(reset=True)
        assert stats.get("advance_hits", 0) == 0, stats
        assert stats.get("advance_declined", 0) >= 1, stats
        assert result.equals(_cold_truth(cluster, tdir, q))
        ctx.close()
    finally:
        cluster.shutdown()


def test_shrunk_or_rewritten_set_never_advances(tdir):
    """Advancement requires a STRICT superset with untouched base files:
    rewriting an existing file (same path, new mtime) must miss the probe
    entirely — changed history is a full recompute, not a fold."""
    cluster = StandaloneCluster(n_executors=2)
    try:
        ctx = BallistaContext(
            *cluster.scheduler_addr,
            settings={"ballista.cache.advance": "true"},
        )
        ctx.register_parquet("t", tdir)
        delta_stats(reset=True)
        ctx.sql(QUERY).collect()
        # rewrite part-0 with different rows AND add part-2: the base fact
        # set no longer holds, so the probe must find nothing
        rng = np.random.default_rng(999)
        pq.write_table(
            pa.table(
                {
                    "g": pa.array(rng.integers(0, 7, 150), type=pa.int64()),
                    "v": pa.array(rng.integers(-50, 50, 150), type=pa.int64()),
                    "w": pa.array(rng.integers(-10, 10, 150), type=pa.int64()),
                    "f": pa.array(rng.random(150), type=pa.float64()),
                }
            ),
            os.path.join(tdir, "part-0.parquet"),
        )
        _write_part(tdir, 2)
        ctx.register_parquet("t", tdir)
        result = ctx.sql(QUERY).collect()
        assert delta_stats(reset=True).get("advance_hits", 0) == 0
        assert result.equals(_cold_truth(cluster, tdir))
        ctx.close()
    finally:
        cluster.shutdown()

"""Test harness config.

JAX tests run on a virtual 8-device CPU mesh
(xla_force_host_platform_device_count), the strategy for validating
multi-chip sharding without TPU pods. Must run before jax is imported.
"""

import os

# Must happen before jax initializes a backend. The axon sitecustomize
# pre-registers the TPU plugin, so the env var alone is not enough — the
# config update below (after import) forces CPU for the test session.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import pyarrow as pa
import pytest

# The persisted layout cache defaults to a cwd-relative directory; tests
# must not leave cache trees in the working copy (cache-specific tests pass
# an explicit tmp dir instead).
import ballista_tpu.config as _config

_config.DEFAULT_SETTINGS[_config.BALLISTA_TPU_LAYOUT_CACHE_DIR] = ""
# Same for the ISSUE 10 cost store: adaptive routing stays ON (the
# structural paths — splits, skew re-plans, build swaps — are exercised by
# the whole suite) but observations never persist across test runs.
_config.DEFAULT_SETTINGS[_config.BALLISTA_TPU_COST_MODEL_DIR] = ""


@pytest.fixture(autouse=True)
def _fresh_cost_store():
    """The in-memory cost store is process-global and configure() only
    clears it on a DIRECTORY change — with the dir pinned to "" above,
    observations would otherwise accumulate across every test in the
    process, and a test's routing (extended tiers, predictions) would
    depend on which device joins happened to run before it. Dropping the
    store per test keeps routing deterministic under any ordering/subset;
    tests that want a warm store seed it explicitly."""
    from ballista_tpu.ops import costmodel

    costmodel.reset(clear_dir=True)
    yield


@pytest.fixture
def sales_table() -> pa.Table:
    """Small deterministic table used across operator tests."""
    return pa.table(
        {
            "id": pa.array(list(range(10)), type=pa.int64()),
            "region": pa.array(
                ["east", "west", "east", "north", "west",
                 "east", "north", "west", "east", "west"]
            ),
            "amount": pa.array(
                [10.0, 20.0, 30.0, 5.0, 15.0, 25.0, 35.0, 45.0, 55.0, 65.0]
            ),
            "qty": pa.array([1, 2, 3, 4, 5, 6, 7, 8, 9, 10], type=pa.int32()),
        }
    )


@pytest.fixture
def ctx():
    from ballista_tpu.engine import ExecutionContext

    return ExecutionContext()


# -- multi-process collective capability probe ------------------------------
# Some CPU jax builds cannot compile cross-process collectives ("Multiprocess
# computations aren't implemented on the CPU backend"): the two-process
# test_multihost mesh tests then fall back to path="host" and fail on the
# path assertion — an environment limit, not a code regression (ROADMAP).
# Probe ONCE per session with a real 2-process shard_map psum (the exact
# mechanism the production pod path uses) and let those tests skip cleanly.
# TPU images (and CPU builds with working Gloo collectives) pass the probe,
# so real mesh-path regressions still fail loudly there.

_MP_PROBE_SCRIPT = r"""
import sys

pid, port = int(sys.argv[1]), sys.argv[2]
import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4"
).strip()
sys.path.insert(0, sys.argv[3])

import jax

jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    f"127.0.0.1:{port}", num_processes=2, process_id=pid
)

import numpy as np
from jax.sharding import PartitionSpec as P

from ballista_tpu.parallel import multihost as mh
from ballista_tpu.parallel.mesh import build_mesh
from ballista_tpu.parallel.meshcompat import shard_map

n = len(jax.devices())
mesh = build_mesh({"data": n})
blocks = {i: np.ones(4, np.float32) for i in mh.local_shard_ids(mesh)}
g = mh.make_sharded(mesh, blocks, 4 * n, np.float32)
fn = jax.jit(shard_map(
    lambda x: jax.lax.psum(x.sum(), "data"),
    mesh=mesh, in_specs=(P("data"),), out_specs=P(), check_vma=False,
))
out = float(np.asarray(fn(g)))
assert out == 4.0 * n, out
print("MULTIPROCESS_OK")
"""

_mp_probe_result = None


def multiprocess_collectives_supported() -> bool:
    """Session-cached 2-process probe; True when the backend can run the
    production multi-process mesh program."""
    global _mp_probe_result
    if _mp_probe_result is not None:
        return _mp_probe_result
    import socket
    import subprocess
    import sys as _sys
    import tempfile

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    repo = str(pathlib.Path(__file__).resolve().parent.parent)
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    with tempfile.NamedTemporaryFile("w", suffix=".py", delete=False) as f:
        f.write(_MP_PROBE_SCRIPT)
        script = f.name
    procs = [
        subprocess.Popen(
            [_sys.executable, script, str(pid), str(port), repo],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for pid in range(2)
    ]
    ok = True
    backend_limit = False
    for p in procs:
        try:
            out, _ = p.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            p.kill()
            out = ""
        ok = ok and p.returncode == 0 and "MULTIPROCESS_OK" in (out or "")
        if "Multiprocess computations aren't implemented" in (out or ""):
            backend_limit = True
    try:
        os.unlink(script)
    except OSError:
        pass
    # Skip ONLY on the known backend limit. Any other probe failure (a
    # regression in make_sharded/meshcompat/build_mesh, a timeout, a port
    # clash) reports "supported" so the real tests RUN and fail loudly
    # instead of silently skipping a production regression.
    _mp_probe_result = ok or not backend_limit
    return _mp_probe_result


@pytest.fixture(scope="session")
def multiprocess_mesh():
    """Skip (not fail) multi-process mesh-path tests on backends that cannot
    compile cross-process collectives."""
    if not multiprocess_collectives_supported():
        pytest.skip(
            "backend cannot run 2-process collectives "
            "(\"Multiprocess computations aren't implemented\") — "
            "environment limit, see ROADMAP"
        )

"""Test harness config.

JAX tests run on a virtual 8-device CPU mesh
(xla_force_host_platform_device_count), the strategy for validating
multi-chip sharding without TPU pods. Must run before jax is imported.
"""

import os

# Must happen before jax initializes a backend. The axon sitecustomize
# pre-registers the TPU plugin, so the env var alone is not enough — the
# config update below (after import) forces CPU for the test session.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import pyarrow as pa
import pytest

# The persisted layout cache defaults to a cwd-relative directory; tests
# must not leave cache trees in the working copy (cache-specific tests pass
# an explicit tmp dir instead).
import ballista_tpu.config as _config

_config.DEFAULT_SETTINGS[_config.BALLISTA_TPU_LAYOUT_CACHE_DIR] = ""


@pytest.fixture
def sales_table() -> pa.Table:
    """Small deterministic table used across operator tests."""
    return pa.table(
        {
            "id": pa.array(list(range(10)), type=pa.int64()),
            "region": pa.array(
                ["east", "west", "east", "north", "west",
                 "east", "north", "west", "east", "west"]
            ),
            "amount": pa.array(
                [10.0, 20.0, 30.0, 5.0, 15.0, 25.0, 35.0, 45.0, 55.0, 65.0]
            ),
            "qty": pa.array([1, 2, 3, 4, 5, 6, 7, 8, 9, 10], type=pa.int32()),
        }
    )


@pytest.fixture
def ctx():
    from ballista_tpu.engine import ExecutionContext

    return ExecutionContext()

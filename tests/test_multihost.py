"""Multi-host SPMD (parallel/multihost.py + SpmdAggregateExec pod path):
a REAL 2-process x 4-device CPU mesh (jax.distributed, Gloo collectives)
where each process reads only the partitions its local shards own, the
distinct-key union is exchanged collectively, and the production shard_map
program runs over the global mesh. SURVEY §2.8: partitions -> shards on a
pod; the reference's analog is one executor per node over NCCL/MPI."""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from ballista_tpu.parallel import multihost as mh

N_PARTS = 8


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _dataset(tmp_path, seed=5):
    rng = np.random.default_rng(seed)
    d = tmp_path / "t"
    d.mkdir()
    tables = []
    for p in range(N_PARTS):
        n = 4000 + p * 111  # uneven partitions
        t = pa.table(
            {
                "k": pa.array(rng.integers(0, 40, n), type=pa.int64()),
                "hk": pa.array(rng.integers(0, 5000, n), type=pa.int64()),
                "s": pa.array([f"s{i % 6}" for i in range(n)]),
                "v": pa.array(rng.uniform(-10, 10, n)),
                "w": pa.array(rng.integers(-100, 100, n), type=pa.int64()),
            }
        )
        pq.write_table(t, str(d / f"part-{p}.parquet"))
        tables.append(t)
    return d, pa.concat_tables(tables)


def _run_workers(data_dir, query):
    """Workers write to FILES, not pipes: a >64 KB result JSON would fill
    the pipe while this parent drains workers sequentially — the blocked
    writer then never reaches jax.distributed.shutdown and the coordination
    barrier kills the whole pod at its 300 s timeout."""
    port = _free_port()
    base = str(data_dir)
    procs = []
    for pid in range(2):
        fo = open(f"{base}.out{pid}", "w")
        fe = open(f"{base}.err{pid}", "w")
        procs.append(
            (
                subprocess.Popen(
                    [sys.executable,
                     os.path.join(os.path.dirname(__file__), "mh_worker.py"),
                     str(pid), "2", str(port), str(data_dir), query],
                    stdout=fo, stderr=fe,
                    env={k: v for k, v in os.environ.items()
                         if k not in ("XLA_FLAGS", "JAX_PLATFORMS")},
                ),
                fo, fe,
            )
        )
    outs = []
    for pid, (p, fo, fe) in enumerate(procs):
        rc = p.wait(timeout=300)
        fo.close()
        fe.close()
        err = open(f"{base}.err{pid}").read()
        assert rc == 0, f"worker {pid} failed:\n{err[-3000:]}"
        out = open(f"{base}.out{pid}").read()
        outs.append(json.loads(out.strip().splitlines()[-1]))
    return outs


def _oracle(table, key):
    g = (
        table.group_by(key)
        .aggregate([("v", "sum"), ("v", "count"), ("v", "min"), ("w", "sum")])
        .sort_by(key)
    )
    return {
        key: g.column(key).to_pylist(),
        "sv": [round(v, 4) for v in g.column("v_sum").to_pylist()],
        "c": g.column("v_count").to_pylist(),
        "mn": [round(v, 6) for v in g.column("v_min").to_pylist()],
        "sw": g.column("w_sum").to_pylist(),
    }


def test_two_process_mesh_aggregation(tmp_path, multiprocess_mesh):
    d, full = _dataset(tmp_path)
    outs = _run_workers(d, "int_keys")

    # both processes took the mesh path and agree on the result
    assert [o["path"] for o in outs] == ["mesh", "mesh"]
    assert outs[0]["result"] == outs[1]["result"]

    # each process read ONLY its own shards' partitions; together they
    # covered every partition exactly once (multihost.partition_shard)
    r0 = set(outs[0]["read_partitions"])
    r1 = set(outs[1]["read_partitions"])
    assert r0.isdisjoint(r1)
    assert r0 | r1 == set(range(N_PARTS))
    # with 8 shards over 2 processes, shards 0-3 / 4-7 split the partitions
    assert r0 == {p for p in range(N_PARTS) if (p % 8) < 4}

    oracle = _oracle(full, "k")
    res = outs[0]["result"]
    assert res["k"] == oracle["k"]
    assert res["c"] == oracle["c"]
    assert res["sw"] == oracle["sw"]
    np.testing.assert_allclose(res["sv"], oracle["sv"], rtol=1e-4)
    np.testing.assert_allclose(res["mn"], oracle["mn"], rtol=1e-5)


def test_string_keys_decline_collectively(tmp_path):
    """v1 multi-host scope excludes string columns; BOTH processes must
    fall back (a unilateral decline would hang the pod) and still agree
    with the oracle."""
    d, full = _dataset(tmp_path)
    outs = _run_workers(d, "string_keys")
    assert [o["path"] for o in outs] == ["host", "host"]
    assert outs[0]["result"] == outs[1]["result"]
    oracle = _oracle(full, "s")
    res = outs[0]["result"]
    assert res["s"] == oracle["s"]
    assert res["c"] == oracle["c"]
    np.testing.assert_allclose(res["sv"], oracle["sv"], rtol=1e-4)


def test_partition_ownership_contract():
    """The host-boundary rule is pure code: partition -> shard -> host."""
    assert [mh.partition_shard(p, 8) for p in range(10)] == [
        0, 1, 2, 3, 4, 5, 6, 7, 0, 1,
    ]


def test_two_process_highcard_sorted_program(tmp_path, multiprocess_mesh):
    """G > MAX_GROUPS on the pod: each process builds its shards' sorted
    chunked-segment tiles with collectively-unified L1/V, and the sorted
    shard_map program (segment fold + psum) runs over the global mesh."""
    d, full = _dataset(tmp_path)
    outs = _run_workers(d, "highcard")
    assert [o["path"] for o in outs] == ["mesh", "mesh"]
    assert outs[0]["result"] == outs[1]["result"]
    r0 = set(outs[0]["read_partitions"])
    r1 = set(outs[1]["read_partitions"])
    assert r0.isdisjoint(r1) and r0 | r1 == set(range(N_PARTS))

    oracle = _oracle(full, "hk")
    res = outs[0]["result"]
    assert len(res["hk"]) > 1024, "not a sorted-path cardinality"
    assert res["hk"] == oracle["hk"]
    assert res["c"] == oracle["c"]
    assert res["sw"] == oracle["sw"]
    # atol: f32 sums of +/-10 values cancel toward zero, where rtol alone
    # explodes on a 3e-5 absolute difference
    np.testing.assert_allclose(res["sv"], oracle["sv"], rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(res["mn"], oracle["mn"], rtol=1e-5)

"""HBM-resident cross-stage exchange tier (ISSUE 16).

The invariant under test everywhere: registering shuffle pieces in the
executor's in-memory exchange registry is PURE acceleration — the Arrow
piece on disk/shared storage stays the authoritative home, so eviction
(budget or chaos), executor death, stale attempts, and scheduler GC all
degrade silently down the storage -> Flight peer -> lineage ladder with
bit-identical results and zero extra task retries. The scheduler's
locality preference and the shared-store GC ride the same hints and must
never outrank fair-share order or break completed-job restarts.
"""

import os

import numpy as np
import pyarrow as pa
import pytest

from ballista_tpu.config import BallistaConfig
from ballista_tpu.ops import costmodel, exchange
from ballista_tpu.ops.runtime import (
    exchange_stats,
    recovery_stats,
    shuffle_tier_stats,
)
from ballista_tpu.proto import ballista_pb2 as pb

GROUP_SQL = (
    "select region, sum(amount) as s from sales group by region order by region"
)


@pytest.fixture(autouse=True)
def _clean_registry():
    exchange.reset()
    exchange_stats(reset=True)
    yield
    exchange.reset()
    exchange_stats(reset=True)


@pytest.fixture
def cm(tmp_path):
    """Cost model bound to a throwaway store (module-global, like the
    registry itself)."""
    costmodel.reset(clear_dir=True)
    cfg = BallistaConfig({
        "ballista.tpu.cost_model": "true",
        "ballista.tpu.cost_model_dir": str(tmp_path / "costs"),
    })
    costmodel.configure(cfg)
    yield cfg
    costmodel.reset(clear_dir=True)


def _batch(n, fill=1):
    return pa.record_batch({"v": pa.array([fill] * n, type=pa.int64())})


# -- registry unit behavior ---------------------------------------------------

def test_publish_resolve_roundtrip_and_counters():
    b = _batch(8)
    kept = exchange.publish(
        "e1", "job", 2, 0, 0, [b], b.schema, attempt=0,
        path="/w/job/2/0/0.arrow", budget=1 << 20,
    )
    assert kept
    hit = exchange.resolve("e1", "job", 2, 0, 0)
    assert hit is not None
    batches, nbytes = hit
    assert batches[0].equals(b) and nbytes == b.nbytes
    # keyed per executor: a peer in the SAME process must never see it
    assert exchange.resolve("e2", "job", 2, 0, 0) is None
    # path-keyed lookup (the Flight service's view)
    schema, pbatches, _ = exchange.resolve_path("/w/job/2/0/0.arrow")
    assert schema == b.schema and pbatches[0].equals(b)
    assert exchange.resident_bytes() == b.nbytes
    assert exchange.stage_resident("e1", "job", 2, 0)
    assert not exchange.stage_resident("e1", "job", 2, 1)
    s = exchange_stats(reset=True)
    assert s.get("published") == 1 and s.get("publish_bytes") == b.nbytes


def test_publish_rejects_over_budget_piece():
    b = _batch(100)
    assert not exchange.publish(
        "e1", "j", 1, 0, 0, [b], b.schema, attempt=0, path="/p",
        budget=b.nbytes - 1,
    )
    assert exchange.resolve("e1", "j", 1, 0, 0) is None
    assert exchange_stats(reset=True).get("skipped_budget") == 1


def test_budget_eviction_is_cost_gated_by_size(cm):
    """Cold model: predicted savings are bytes-proportional, so a small
    incomer cannot displace a bigger victim — but a bigger incomer evicts
    the smaller LRU entry."""
    big, small = _batch(100), _batch(25)
    budget = big.nbytes + small.nbytes - 8  # either alone fits, both don't
    assert exchange.publish("e1", "j", 1, 0, 0, [big], big.schema, 0,
                            "/p/big", budget)
    # smaller incomer: victim's predicted saving exceeds the incomer's
    assert not exchange.publish("e1", "j", 1, 1, 0, [small], small.schema, 0,
                                "/p/small", budget)
    assert exchange.resolve("e1", "j", 1, 0, 0) is not None
    assert exchange_stats(reset=True).get("skipped_budget") == 1
    # bigger incomer displaces the smaller resident
    exchange.reset()
    assert exchange.publish("e1", "j", 1, 1, 0, [small], small.schema, 0,
                            "/p/small", budget)
    assert exchange.publish("e1", "j", 1, 0, 0, [big], big.schema, 0,
                            "/p/big", budget)
    assert exchange.resolve("e1", "j", 1, 1, 0) is None
    assert exchange.resolve("e1", "j", 1, 0, 0) is not None
    assert exchange_stats(reset=True).get("evicted_budget") == 1


def test_budget_eviction_prices_at_observed_rates(cm):
    """The keep/evict decision consults the cost model's OBSERVED h2d +
    readback rates, not just sizes: a small entry whose bucket observed
    pathologically slow transfers outprices a byte-bigger incomer."""
    big, small = _batch(100), _batch(25)
    # the small entry's bucket transfers at a crawl; the big one's is fast
    costmodel.seed("h2d", float(small.nbytes), 10.0)
    costmodel.seed("readback", float(small.nbytes), 10.0)
    costmodel.seed("h2d", float(big.nbytes), 1e-6)
    costmodel.seed("readback", float(big.nbytes), 1e-6)
    budget = big.nbytes + small.nbytes - 8
    assert exchange.publish("e1", "j", 1, 1, 0, [small], small.schema, 0,
                            "/p/small", budget)
    # byte-bigger incomer now LOSES: evicting the slow-bucket entry would
    # forfeit more predicted transfer seconds than the incomer saves
    assert not exchange.publish("e1", "j", 1, 0, 0, [big], big.schema, 0,
                                "/p/big", budget)
    assert exchange.resolve("e1", "j", 1, 1, 0) is not None
    assert exchange_stats(reset=True).get("skipped_budget") == 1


def test_republish_newest_attempt_wins():
    """Speculation promotion / retry re-publish: the registry keeps exactly
    one entry per piece and the NEWEST attempt's batches (any attempt's
    output is bit-identical — the repo invariant — so serving it is
    always sound; the attempt is tracked for exactly this pin)."""
    b0, b1 = _batch(8, fill=1), _batch(8, fill=1)
    assert exchange.publish("e1", "j", 1, 0, 0, [b0], b0.schema, 0,
                            "/p/a0", 1 << 20)
    assert exchange.attempt_of("e1", "j", 1, 0, 0) == 0
    assert exchange.publish("e1", "j", 1, 0, 0, [b1], b1.schema, 2,
                            "/p/a2", 1 << 20)
    assert exchange.attempt_of("e1", "j", 1, 0, 0) == 2
    # the stale attempt's path no longer resolves; the new one does
    assert exchange.resolve_path("/p/a0") is None
    assert exchange.resolve_path("/p/a2") is not None
    assert exchange.resident_bytes() == b1.nbytes


def test_tenant_budget_enforced_before_global(cm):
    """ISSUE 19 satellite: a tenant at its residency cap evicts ITS OWN
    LRU entries (cost-gated, like the global policy) and can never
    displace another tenant's bytes to fit itself — the per-tenant ledger
    follows every publish and drop."""
    a1, a2, b1 = _batch(50), _batch(50), _batch(50)
    budget = 1 << 20  # the global cap never binds in this test
    t_budget = a1.nbytes + 8  # one piece per tenant fits, two do not
    assert exchange.publish("e1", "j", 1, 0, 0, [a1], a1.schema, 0, "/a1",
                            budget, tenant="alice", tenant_budget=t_budget)
    assert exchange.publish("e1", "j", 1, 1, 0, [b1], b1.schema, 0, "/b1",
                            budget, tenant="bob", tenant_budget=t_budget)
    assert exchange.tenant_resident_bytes("alice") == a1.nbytes
    assert exchange.tenant_resident_bytes("bob") == b1.nbytes
    # alice's second piece (equal saving): evicts HER LRU piece, not bob's
    assert exchange.publish("e1", "j", 1, 2, 0, [a2], a2.schema, 0, "/a2",
                            budget, tenant="alice", tenant_budget=t_budget)
    assert exchange.resolve("e1", "j", 1, 0, 0) is None  # a1 evicted
    assert exchange.resolve("e1", "j", 1, 1, 0) is not None  # bob intact
    assert exchange.resolve("e1", "j", 1, 2, 0) is not None
    assert exchange.tenant_resident_bytes("alice") == a2.nbytes
    assert exchange.tenant_resident_bytes("bob") == b1.nbytes
    s = exchange_stats(reset=True)
    assert s.get("evicted_tenant_budget") == 1, s
    assert not s.get("evicted_budget"), s


def test_tenant_budget_cost_gate_keeps_warmer_own_entry(cm):
    """Within one tenant the same cost gate applies: a smaller incomer
    whose predicted saving trails its own bigger resident's is skipped
    rather than evicting it."""
    big, small = _batch(100), _batch(25)
    t_budget = big.nbytes + 8
    assert exchange.publish("e1", "j", 1, 0, 0, [big], big.schema, 0,
                            "/big", 1 << 20,
                            tenant="alice", tenant_budget=t_budget)
    assert not exchange.publish("e1", "j", 1, 1, 0, [small], small.schema, 0,
                                "/small", 1 << 20,
                                tenant="alice", tenant_budget=t_budget)
    assert exchange.resolve("e1", "j", 1, 0, 0) is not None
    assert exchange.tenant_resident_bytes("alice") == big.nbytes
    s = exchange_stats(reset=True)
    assert s.get("skipped_budget") == 1, s
    # a single piece bigger than the tenant cap is rejected outright
    assert not exchange.publish("e1", "j", 1, 2, 0, [big], big.schema, 0,
                                "/big2", 1 << 20,
                                tenant="bob", tenant_budget=big.nbytes - 1)
    assert exchange.tenant_resident_bytes("bob") == 0


def test_tenant_budget_plumbed_from_job_settings():
    """End-to-end: ballista.tenant.residency_budget_bytes rides the job's
    settings into the executor's capture — an over-cap tenant's pieces
    are skipped (ladder reads, correct result), an uncapped run keeps
    registering."""
    t = _sales()
    capped_out, capped_stats, _ = _run_cluster(t, {
        "ballista.tenant.name": "alice",
        "ballista.tenant.residency_budget_bytes": "1",
    })
    plain_out, plain_stats, _ = _run_cluster(t, {})
    assert capped_out.equals(plain_out)
    assert capped_stats.get("published", 0) == 0, capped_stats
    assert capped_stats.get("skipped_budget", 0) >= 1, capped_stats
    assert plain_stats.get("published", 0) >= 1, plain_stats


def test_evict_and_evict_job():
    b = _batch(4)
    exchange.publish("e1", "ja", 1, 0, 0, [b], b.schema, 0, "/pa", 1 << 20)
    exchange.publish("e1", "jb", 1, 0, 0, [b], b.schema, 0, "/pb", 1 << 20)
    assert exchange.evict("e1", "ja", 1, 0, 0)
    assert not exchange.evict("e1", "ja", 1, 0, 0)
    assert exchange.evict_job("jb") == 1
    assert exchange.resident_bytes() == 0


# -- scheduler locality preference --------------------------------------------

def _state(config=None):
    from ballista_tpu.scheduler.kv import MemoryBackend
    from ballista_tpu.scheduler.state import SchedulerState

    return SchedulerState(
        MemoryBackend(), "exch",
        config=config or BallistaConfig({"ballista.tpu.cost_model_dir": ""}),
    )


def _identity_reader(residents):
    """Identity ShuffleReaderExec whose map outputs live on the executors
    named in `residents` (executor_id, resident, nbytes) triples."""
    from ballista_tpu.distributed.stages import ShuffleLocation, ShuffleReaderExec

    locs = [
        ShuffleLocation(eid, "h", 1, f"/x/{i}", stage_id=1, map_partition=i,
                        resident=res, nbytes=nb)
        for i, (eid, res, nb) in enumerate(residents)
    ]
    schema = pa.schema([("v", pa.int64())])
    return ShuffleReaderExec(locs, schema, len(locs), identity=True)


def test_locality_order_prefers_resident_partitions():
    """Partitions whose resident inputs live on THIS executor come first,
    biggest predicted saving first; everything else keeps the pinned
    sorted-by-str order (and an executor with nothing resident sees
    exactly that baseline order)."""
    st = _state()
    plan = _identity_reader([
        ("e1", False, 100), ("e2", True, 100),
        ("e1", True, 10_000_000), ("e1", True, 100),
    ])
    parts = {0, 1, 2, 3}
    ordered, preferred = st._locality_partition_order(plan, parts, "e1")
    assert preferred == {2, 3}
    assert ordered[0] == 2  # 10 MB resident beats 100 B resident
    assert ordered[1] == 3
    assert ordered[2:] == [0, 1]  # non-resident tail keeps baseline order
    base, none_pref = st._locality_partition_order(plan, parts, "e9")
    assert none_pref == set()
    assert base == sorted(parts, key=str)


def test_locality_order_is_uniform_for_hash_readers():
    """A non-identity reader consumes a slice of EVERY map output — no
    partition is more local than another, so the order stays the baseline."""
    from ballista_tpu.distributed.stages import ShuffleLocation, ShuffleReaderExec

    st = _state()
    locs = [
        ShuffleLocation("e1", "h", 1, "/x/0", stage_id=1, map_partition=0,
                        resident=True, nbytes=1000),
    ]
    plan = ShuffleReaderExec(locs, pa.schema([("v", pa.int64())]), 4,
                             identity=False)
    ordered, preferred = st._locality_partition_order(plan, {0, 1, 2, 3}, "e1")
    assert preferred == set()
    assert ordered == sorted({0, 1, 2, 3}, key=str)


# -- scheduler-led shared-store GC --------------------------------------------

def _completed_task(job, stage, part, storage_uri=""):
    t = pb.TaskStatus()
    t.partition_id.job_id = job
    t.partition_id.stage_id = stage
    t.partition_id.partition_id = part
    t.completed.executor_id = "e1"
    t.completed.path = storage_uri or f"/w/{job}/{stage}/{part}"
    t.completed.storage_uri = storage_uri
    return t


def test_gc_shared_store_job_sweeps_by_terminal_kind(tmp_path):
    root = tmp_path / "store"
    tasks = []
    for stage in (1, 2, 3):
        base = root / "jobc" / str(stage) / "0"
        base.mkdir(parents=True)
        (base / "0.arrow").write_bytes(b"x")
        tasks.append(_completed_task("jobc", stage, 0, str(base)))
    st = _state()
    shuffle_tier_stats(reset=True)
    # completed: intermediates sweep, the final stage stays for the client
    assert st._gc_shared_store_job("jobc", 3, tasks) == 2
    assert sorted(os.listdir(root / "jobc")) == ["3"]
    # failed: everything releases, the emptied job dir prunes with it
    assert st._gc_shared_store_job("jobc", None, tasks) == 1
    assert not (root / "jobc").exists()
    assert shuffle_tier_stats(reset=True).get("gc_stage_swept") == 3
    # work-dir-homed tasks (empty storage_uri) are never the scheduler's
    assert st._gc_shared_store_job(
        "jobl", None, [_completed_task("jobl", 1, 0)]
    ) == 0
    # a uri whose tail does not spell the task's own plan coordinates
    # never steers a delete (hostile or corrupt report)
    evil = tmp_path / "elsewhere"
    evil.mkdir()
    assert st._gc_shared_store_job(
        "jobc", None, [_completed_task("jobc", 1, 0, str(evil))]
    ) == 0
    assert evil.exists()


def test_result_cache_delete_sweeps_cached_final_stage(tmp_path):
    """Every way an entry leaves the cache releases its storage-homed
    result pieces: explicit invalidation and LRU eviction both sweep the
    job dir (the intermediates went at job completion)."""
    root = tmp_path / "store"
    cfg = BallistaConfig({
        "ballista.cache.results.max_entries": "1",
    })
    st = _state(cfg)

    def put(fp, job):
        base = root / job / "3" / "0"
        base.mkdir(parents=True)
        (base / "0.arrow").write_bytes(b"x")
        done = pb.CompletedJob()
        pl = done.partition_location.add()
        pl.partition_id.job_id = job
        pl.partition_id.stage_id = 3
        pl.partition_id.partition_id = 0
        pl.path = str(base)
        pl.storage_uri = str(base)
        assert st.result_cache_put(fp, done)

    shuffle_tier_stats(reset=True)
    put("fp-a", "joba")
    st.result_cache_invalidate("fp-a")
    assert not (root / "joba").exists()
    # LRU eviction (cap 1): inserting fp-c evicts fp-b and sweeps its job
    put("fp-b", "jobb")
    put("fp-c", "jobc")
    assert not (root / "jobb").exists()
    assert (root / "jobc").exists()
    assert shuffle_tier_stats(reset=True).get("gc_result_swept") == 2


# -- end to end ---------------------------------------------------------------

def _sales(n=2000, seed=0):
    rng = np.random.default_rng(seed)
    return pa.table({
        "region": pa.array(
            np.array(["east", "west", "north", "south"])[rng.integers(0, 4, n)]
        ),
        "amount": pa.array(rng.uniform(0, 100, n)),
    })


def _run_cluster(table, settings, n_executors=1):
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.executor.runtime import StandaloneCluster

    exchange.reset()
    exchange_stats(reset=True)
    recovery_stats(reset=True)
    cluster = StandaloneCluster(n_executors=n_executors)
    try:
        ctx = BallistaContext(*cluster.scheduler_addr, settings={
            "ballista.shuffle.partitions": "4",
            "ballista.cache.results": "false",
            **settings,
        })
        ctx.register_record_batches("sales", table, n_partitions=4)
        out = ctx.sql(GROUP_SQL).collect()
        ctx.close()
    finally:
        cluster.shutdown()
    return out, exchange_stats(reset=True), recovery_stats(reset=True)


def test_same_executor_consumer_skips_reupload_bit_identical():
    """ISSUE 16 acceptance: on a single-executor 2-stage run the reduce
    side resolves every local map piece from the registry (zero decode,
    zero h2d) — and the result is bit-identical to the exchange-off run."""
    t = _sales()
    on_out, on_stats, on_rec = _run_cluster(t, {})
    off_out, off_stats, _ = _run_cluster(t, {"ballista.tpu.exchange": "false"})
    assert on_out.equals(off_out)
    assert on_stats.get("published", 0) >= 1, on_stats
    assert on_stats.get("reupload_skipped", 0) >= 1, on_stats
    assert on_stats.get("h2d_bytes_saved", 0) > 0, on_stats
    assert on_rec.get("task_retry", 0) == 0, on_rec
    assert off_stats == {}, off_stats


def test_exchange_evict_chaos_degrades_to_ladder_zero_retries():
    """Every consume-time probe torn by exchange.evict chaos (rate 1.0):
    entries are dropped at the seam and every read walks the authoritative
    piece ladder — bit-identical to the exchange-off run, ZERO task
    retries, zero lineage events (the loss of a residency entry is not a
    data loss)."""
    t = _sales()
    chaos_out, cs, cr = _run_cluster(t, {
        "ballista.chaos.rate": "1.0",
        "ballista.chaos.seed": "5",
        "ballista.chaos.sites": "exchange.evict",
    })
    plain_out, _, _ = _run_cluster(t, {"ballista.tpu.exchange": "false"})
    assert chaos_out.equals(plain_out)
    assert cs.get("evicted_chaos", 0) >= 1, cs
    assert cs.get("reupload_skipped", 0) == 0, cs
    assert cs.get("miss", 0) >= 1, cs
    assert cr.get("chaos_injected", 0) >= 1, cr
    for event in ("task_retry", "fetch_failed", "map_recomputed"):
        assert cr.get(event, 0) == 0, (event, cr)


def test_executor_death_with_resident_only_consumer_recovers():
    """The registry dies with its executor: a consumer whose inputs were
    resident ONLY on the dead executor must recover through the ordinary
    Flight/lineage ladder (stale `resident` hints on completed tasks are
    advisory, never load-bearing) — results stay correct."""
    import ballista_tpu.scheduler.state as state_mod
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.executor.runtime import StandaloneCluster

    exchange.reset()
    recovery_stats(reset=True)
    cluster = StandaloneCluster(n_executors=2)
    old_lease = state_mod.EXECUTOR_LEASE_SECS
    state_mod.EXECUTOR_LEASE_SECS = 1.0
    cluster.scheduler_impl.lost_task_check_interval = 0.3
    try:
        t = _sales()
        ctx = BallistaContext(*cluster.scheduler_addr, settings={
            "ballista.shuffle.partitions": "4",
            "ballista.cache.results": "false",
        })
        ctx.register_record_batches("sales", t, n_partitions=4)
        plan = ctx.sql(GROUP_SQL).logical_plan()
        job_id = ctx.submit(plan)
        status = ctx._wait_for_job(job_id, timeout=60.0)
        owners = {
            pl.executor_meta.id
            for pl in status.completed.partition_location
        }
        victim = next(ex for ex in cluster.executors if ex.id in owners)
        # the victim's registry entries die with it — drop them explicitly
        # too, mirroring a real process death inside this shared process
        victim.stop()
        exchange.reset()
        out = ctx._collect_results(job_id, plan.schema(), timeout=120.0)
        ctx.close()
        expected = (
            t.group_by("region").aggregate([("amount", "sum")])
            .rename_columns(["region", "s"]).sort_by("region")
        )
        got = out.sort_by("region")
        assert got.column("region").to_pylist() == expected.column(
            "region").to_pylist()
        np.testing.assert_allclose(
            got.column("s").to_pylist(), expected.column("s").to_pylist()
        )
        stats = recovery_stats(reset=True)
        assert stats.get("result_partition_restarted", 0) > 0, stats
    finally:
        state_mod.EXECUTOR_LEASE_SECS = old_lease
        cluster.shutdown()


def test_terminal_gc_sweeps_intermediates_on_shared_tier(tmp_path):
    """End to end: a completed shared-tier job leaves only its final stage
    in the store (the client fetch still works), intermediates swept at
    the terminal transition."""
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.executor.runtime import StandaloneCluster

    shared = tmp_path / "store"
    shared.mkdir()
    shuffle_tier_stats(reset=True)
    cluster = StandaloneCluster(n_executors=1)
    try:
        ctx = BallistaContext(*cluster.scheduler_addr, settings={
            "ballista.shuffle.partitions": "4",
            "ballista.cache.results": "false",
            "ballista.shuffle.tier": "shared",
            "ballista.shuffle.dir": str(shared),
        })
        ctx.register_record_batches("sales", _sales(), n_partitions=4)
        out = ctx.sql(GROUP_SQL).collect()
        ctx.close()
    finally:
        cluster.shutdown()
    assert out.num_rows == 4
    jobs = os.listdir(shared)
    assert len(jobs) == 1, jobs
    stages = os.listdir(shared / jobs[0])
    assert len(stages) == 1, stages  # only the final stage survives
    tier = shuffle_tier_stats(reset=True)
    assert tier.get("gc_stage_swept", 0) >= 1, tier

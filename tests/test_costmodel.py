"""Adaptive execution (ISSUE 10): the measured cost model and its three
consumers.

Contract under test, in order of importance:

1. **Bit-identity** — the cost model changes WHERE a partition runs,
   never what it returns. Every routing outcome here (extended tier,
   partial-offload split, build-side swap, skew re-plan) is asserted
   bit-identical to the host oracle.
2. **Cold-start safety** — a cold, corrupt, or fingerprint-mismatched
   store reproduces the pre-adaptive static routing exactly.
3. **Honest accounting** — every decision lands in the routing
   accumulator, predictions carry their observations, and the mispredict
   accounting sums (mispredicts <= predictions; rate = m/p).
"""

import json
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from ballista_tpu.config import BallistaConfig
from ballista_tpu.engine import ExecutionContext
from ballista_tpu.ops import costmodel, kernels
from ballista_tpu.ops.join import device_join_indices, try_device_inner_join
from ballista_tpu.ops.kernels import (
    JOIN_EXTENDED_TIERS,
    JOIN_GATHER_HARD_CAP,
    JOIN_MULTIPLICITY_TIERS,
    join_extended_tier,
)
from ballista_tpu.ops.runtime import (
    bucket_rows,
    join_path_stats,
    record_routing,
    reset_residency,
    routing_stats,
)
from ballista_tpu.physical.joinutil import join_indices

TOP_TIER = JOIN_MULTIPLICITY_TIERS[-1]


def _fresh():
    kernels._stage_cache.clear()
    kernels._stage_cache_pins.clear()
    kernels._stage_latest.clear()
    reset_residency()
    routing_stats(reset=True)
    join_path_stats(reset=True)


@pytest.fixture
def cm(tmp_path):
    """Cost model bound to a throwaway persisted store, drained routing
    accumulators, and guaranteed post-test reset (the module is process-
    global state, like the stage cache)."""
    _fresh()
    costmodel.reset(clear_dir=True)
    cfg = BallistaConfig({
        "ballista.tpu.cost_model": "true",
        "ballista.tpu.cost_model_dir": str(tmp_path / "costs"),
    })
    costmodel.configure(cfg)
    yield cfg
    costmodel.reset(clear_dir=True)
    _fresh()


# -- store: roundtrip, corruption, fingerprint -------------------------------

def test_store_roundtrip(cm, tmp_path):
    """Observations survive flush + reset (a simulated fresh process
    lazily reloads the persisted entries and predicts from them)."""
    for _ in range(costmodel.MIN_OBSERVATIONS):
        costmodel.observe("op.x", 1024, 0.010)
    costmodel.flush()
    assert (tmp_path / "costs" / "costs.json").exists()
    costmodel.reset()  # fresh process: in-memory store gone, dir kept
    costmodel.configure(cm)
    p = costmodel.predict("op.x", 1024)
    assert p is not None and abs(p - 0.010) < 1e-9


def test_store_corruption_starts_empty(cm, tmp_path):
    d = tmp_path / "costs"
    d.mkdir(parents=True, exist_ok=True)
    (d / "costs.json").write_text("{definitely not json")
    routing_stats(reset=True)
    assert costmodel.predict("op.x", 64) is None
    assert costmodel.snapshot() == {}
    ev = routing_stats(reset=True)["events"]
    assert ev.get("cost_store_corrupt") == 1


def test_store_fingerprint_mismatch_ignored(cm, tmp_path):
    """A store written by a different jax/jaxlib/backend stack must never
    steer this one: ignored wholesale, reason recorded."""
    d = tmp_path / "costs"
    d.mkdir(parents=True, exist_ok=True)
    (d / "costs.json").write_text(json.dumps({
        "format": 1, "fingerprint": "cm1|some-other-stack",
        "entries": {"op.x|device|b64": {"s": 1.0, "units": 64, "n": 99}},
    }))
    routing_stats(reset=True)
    assert costmodel.predict("op.x", 64) is None
    assert routing_stats(reset=True)["events"].get(
        "cost_store_fingerprint_mismatch") == 1


def test_flush_merges_other_writers(cm, tmp_path):
    """Last-writer-wins per KEY, not per file: another process's entries
    for keys we never touched survive our flush."""
    costmodel.seed("ours", 64, 0.001)
    costmodel.flush()
    blob = json.loads((tmp_path / "costs" / "costs.json").read_text())
    blob["entries"]["theirs|device|b64"] = {"s": 0.5, "units": 64, "n": 8}
    (tmp_path / "costs" / "costs.json").write_text(json.dumps(blob))
    costmodel.observe("ours", 64, 0.001)  # dirty again
    costmodel.flush()
    merged = json.loads((tmp_path / "costs" / "costs.json").read_text())
    assert "theirs|device|b64" in merged["entries"]
    assert "ours|device|b64" in merged["entries"]


# -- prediction: buckets, priors, forgetting, retier -------------------------

def test_cold_predict_is_none(cm):
    assert costmodel.predict("never.seen", 1000) is None


def test_exact_bucket_preferred_over_global(cm):
    costmodel.seed("op.y", 64, 0.001)       # 64-bucket: ~1.6e-5 s/unit
    costmodel.seed("op.y", 4096, 0.400)     # 4096-bucket: ~1e-4 s/unit
    p_small = costmodel.predict("op.y", 64)
    p_big = costmodel.predict("op.y", 4096)
    assert abs(p_small - 0.001) < 1e-9
    assert abs(p_big - 0.400) < 1e-9
    # an unseen bucket falls back to the op-global rate (non-None)
    assert costmodel.predict("op.y", 1 << 20) is not None


def test_prediction_needs_min_observations(cm):
    costmodel.observe("op.z", 128, 0.002)  # n=1 < MIN_OBSERVATIONS
    assert costmodel.predict("op.z", 128) is None


def test_exponential_forgetting_bounds_history(cm):
    for _ in range(200):
        costmodel.observe("op.f", 256, 0.001)
    entry = costmodel.snapshot()["op.f|device|b256"]
    # history halves at saturation: n can never run away to 200
    assert entry["n"] <= 2 * 32 + 1


def test_retier_replaces_history(cm):
    costmodel.seed("op.r", 512, 10.0)  # absurdly slow prior
    costmodel.retier("op.r", 512, 0.001)
    p = costmodel.predict("op.r", 512)
    assert p is not None and p < 0.01
    assert routing_stats(reset=True)["events"].get("retier") == 1


def test_check_mispredict_is_symmetric(cm):
    """The canonical check re-tiers on gross deviation in EITHER
    direction (an over-predicted rate suppressing admission is as wrong
    as an under-predicted one admitting too much)."""
    assert not costmodel.check_mispredict("op.c", 64, None, 1.0)
    assert not costmodel.check_mispredict("op.c", 64, 0.010, 0.011)
    assert costmodel.check_mispredict("op.c", 64, 0.001, 0.010)  # slower
    assert costmodel.predict("op.c", 64) == pytest.approx(0.010)
    assert costmodel.check_mispredict("op.c", 64, 0.100, 0.002)  # faster
    assert costmodel.predict("op.c", 64) == pytest.approx(0.002)
    assert routing_stats(reset=True)["events"].get("retier") == 2


def test_disabled_model_noops():
    costmodel.reset(clear_dir=True)
    costmodel.observe("op.off", 64, 1.0)
    assert costmodel.predict("op.off", 64) is None
    assert costmodel.snapshot() == {}


# -- routing accumulator accounting ------------------------------------------

def test_routing_accounting_sums(cm):
    routing_stats(reset=True)
    record_routing("device", "join", 0.010, 0.011)   # fine
    record_routing("device", "join", 0.001, 0.010)   # 10x over: mispredict
    record_routing("host", "join", 0.030, 0.002)     # 15x under: mispredict
    record_routing("split", "join")                  # no prediction
    s = routing_stats(reset=True)
    assert s["engines"] == {"device": 2, "host": 1, "split": 1}
    assert s["predictions"] == 3 and s["mispredicts"] == 2
    assert s["mispredict_rate"] == pytest.approx(2 / 3)
    assert s["predictions"] <= sum(s["engines"].values())
    assert abs(s["predicted_s"] - 0.041) < 1e-9
    assert abs(s["observed_s"] - 0.023) < 1e-9
    # reset drained everything
    s2 = routing_stats()
    assert not s2["engines"] and s2["predictions"] == 0


# -- tier selection units ----------------------------------------------------

def _warm_extended(probe_slots, host_units, dev_s=1e-4, host_s=10.0):
    """Seed the store so the 512 gather looks cheap and the host join
    expensive for the given shape."""
    costmodel.seed("join.gather", probe_slots * JOIN_EXTENDED_TIERS[0], dev_s)
    costmodel.seed("join.host", host_units, host_s, engine="host")


def test_extended_tier_cold_store_declines(cm):
    assert join_extended_tier(TOP_TIER + 10, 1024, 100_000) is None


def test_extended_tier_warm_store_admits(cm):
    _warm_extended(1024, 100_000)
    got = join_extended_tier(TOP_TIER + 10, 1024, 100_000)
    assert got is not None
    tier, dev, host = got
    assert tier == JOIN_EXTENDED_TIERS[0]
    assert dev < 0.75 * host


def test_extended_tier_unfavorable_evidence_declines(cm):
    _warm_extended(1024, 100_000, dev_s=10.0, host_s=1e-4)
    assert join_extended_tier(TOP_TIER + 10, 1024, 100_000) is None


def test_extended_tier_hard_cap_is_absolute(cm):
    """No store, however warm, admits past the hard cap — it bounds the
    worst case a wrong (or adversarial) store can cost."""
    slots = JOIN_GATHER_HARD_CAP // JOIN_EXTENDED_TIERS[0] + 1
    _warm_extended(slots, 100_000)
    assert join_extended_tier(TOP_TIER + 10, slots, 100_000) is None


def test_extended_tier_multiplicity_past_top_extended(cm):
    _warm_extended(1024, 100_000)
    assert join_extended_tier(JOIN_EXTENDED_TIERS[-1] + 1, 1024,
                              100_000) is None


def test_extended_tier_readmits_cap_decline_at_natural_width(cm):
    """A join declined purely on the ELEMENT cap (multiplicity inside the
    static ladder) re-admits at its natural static width under the hard
    cap — not at a 2x-wasteful extended width."""
    from ballista_tpu.ops.kernels import JOIN_GATHER_CAP

    slots = JOIN_GATHER_CAP // TOP_TIER + 1  # past the element cap at 256
    assert slots * TOP_TIER <= JOIN_GATHER_HARD_CAP
    costmodel.seed("join.gather", slots * TOP_TIER, 1e-4)
    costmodel.seed("join.host", 500_000, 10.0, engine="host")
    got = join_extended_tier(TOP_TIER - 6, slots, 500_000)
    assert got is not None and got[0] == TOP_TIER


# -- partial offload: split at the tier boundary -----------------------------

def _skewed_join(monster_mult=TOP_TIER + 60, tail=1500, n_probe=3000, seed=3):
    """Build with ONE monster key past the top static tier + a unique
    tail; probes guaranteed to hit the monster."""
    rng = np.random.default_rng(seed)
    build = np.concatenate([
        np.arange(tail, dtype=np.int64),
        np.full(monster_mult, tail // 2, dtype=np.int64),
    ])
    rng.shuffle(build)
    probe = np.concatenate([
        rng.integers(-1, tail + 50, n_probe - 2).astype(np.int64),
        np.full(2, tail // 2, dtype=np.int64),
    ])
    return build, probe


def _assert_oracle_equal(res, build, probe):
    assert res is not None
    build_idx, probe_idx, counts = res
    bi, pi = join_indices(build, probe, "inner")
    assert build_idx.tolist() == bi.tolist()
    assert probe_idx.tolist() == pi.tolist()
    np.testing.assert_array_equal(counts, np.bincount(pi, minlength=len(probe)))


def test_partial_offload_bit_equality(cm):
    """The acceptance shape: a join just past a static tier boundary runs
    SPLIT (device prefix + host remainder, merged) instead of wholesale
    host — bit-identical to the host oracle, decision recorded."""
    build, probe = _skewed_join()
    res = device_join_indices(build, probe, config=cm)
    _assert_oracle_equal(res, build, probe)
    s = routing_stats(reset=True)
    assert s["engines"].get("split") == 1
    assert s["events"].get("split") == 1
    assert join_path_stats(reset=True)["paths"].get("split") == 1


def test_partial_offload_without_config_keeps_static_contract(cm):
    """Direct callers that pass no config get the pre-adaptive ladder
    exactly: the same shape steps aside wholesale."""
    build, probe = _skewed_join()
    join_path_stats(reset=True)
    assert device_join_indices(build, probe) is None
    assert join_path_stats(reset=True)["paths"] == {"step_aside": 1}


def test_partial_offload_model_off_keeps_static_contract(cm):
    build, probe = _skewed_join()
    off = BallistaConfig({"ballista.tpu.cost_model": "false"})
    join_path_stats(reset=True)
    assert device_join_indices(build, probe, config=off) is None
    assert join_path_stats(reset=True)["paths"] == {"step_aside": 1}


def test_partial_offload_broad_duplication_not_split(cm):
    """Dozens of distinct hot keys is broad duplication, not skew — the
    split escape must not engage (host-wholesale handles it)."""
    rng = np.random.default_rng(9)
    hot_keys = np.arange(24, dtype=np.int64)  # > _SPLIT_MAX_HOT_KEYS
    build = np.concatenate([
        np.repeat(hot_keys, TOP_TIER + 10),
        np.arange(100, 400, dtype=np.int64),
    ])
    rng.shuffle(build)
    probe = np.concatenate([
        np.repeat(hot_keys, 2),
        rng.integers(0, 400, 500).astype(np.int64),
    ])
    join_path_stats(reset=True)
    assert device_join_indices(build, probe, config=cm) is None
    assert join_path_stats(reset=True)["paths"] == {"step_aside": 1}


# -- extended admission e2e + mispredict-driven re-tiering -------------------

def test_warm_store_runs_previously_declined_shape(cm):
    """ISSUE 10 acceptance: with a warm cost store, a multiplicity-300
    join the static ladder declines runs ON DEVICE at an extended tier,
    bit-identical to the host oracle."""
    build, probe = _skewed_join(monster_mult=300)
    probe_slots = bucket_rows(len(probe), 16)
    _warm_extended(probe_slots, len(build) + len(probe))
    join_path_stats(reset=True)
    res = device_join_indices(build, probe, config=cm)
    _assert_oracle_equal(res, build, probe)
    s = routing_stats(reset=True)
    assert s["engines"].get("device") == 1
    assert join_path_stats(reset=True)["paths"].get("device") == 1


def test_mispredict_retier_pulls_admission_back(cm):
    """An over-eager store admits an extended tier once; the gross
    mispredict REPLACES the bucket's history with the observed cost, and
    the very next decision for the shape falls back to the static
    ladder."""
    # 20 distinct hot keys: NOT a split candidate, so the post-retier
    # decision is a clean step-aside, not a split
    hot = np.repeat(np.arange(20, dtype=np.int64), 300)
    build = np.concatenate([hot, np.arange(100, 1100, dtype=np.int64)])
    rng = np.random.default_rng(11)
    rng.shuffle(build)
    probe = np.concatenate([
        np.arange(20, dtype=np.int64),
        rng.integers(0, 1100, 800).astype(np.int64),
    ])
    probe_slots = bucket_rows(len(probe), 16)
    # absurdly fast gather prior + a host prior slow enough to admit but
    # fast enough that the REAL gather cost loses to it after the retier
    costmodel.seed("join.gather", probe_slots * JOIN_EXTENDED_TIERS[0], 1e-9)
    costmodel.seed("join.host", len(build) + len(probe), 0.002, engine="host")
    res = device_join_indices(build, probe, config=cm)
    _assert_oracle_equal(res, build, probe)
    s = routing_stats(reset=True)
    assert s["engines"].get("device") == 1
    assert s["events"].get("retier", 0) >= 1
    assert s["mispredicts"] >= 1
    # the store now predicts the REAL gather cost (compile included),
    # which loses to the seeded host rate: static ladder again
    join_path_stats(reset=True)
    assert device_join_indices(build, probe, config=cm) is None
    assert join_path_stats(reset=True)["paths"] == {"step_aside": 1}


# -- runtime re-planning: build-side swap ------------------------------------

def test_build_side_swap_bit_identity(cm):
    """A planned build side 4x+ larger than the probe swaps sides on
    device (sort the smaller plane); the restored probe-major order is
    bit-identical to the unswapped run and the host oracle."""
    rng = np.random.default_rng(13)
    build = pa.table({"bk": pa.array(np.arange(9000), type=pa.int64())})
    pk = rng.integers(0, 9500, 400)
    probe = pa.table({"pk": pa.array(pk, type=pa.int64())})
    routing_stats(reset=True)
    swapped = try_device_inner_join(build, probe, ["bk"], ["pk"], config=cm)
    assert routing_stats(reset=True)["events"].get("join_build_swapped") == 1
    plain = try_device_inner_join(build, probe, ["bk"], ["pk"])
    assert swapped is not None and plain is not None
    np.testing.assert_array_equal(swapped[0], plain[0])
    np.testing.assert_array_equal(swapped[1], plain[1])


def test_failed_build_swap_records_one_decision(cm):
    """A speculative swap whose swapped shape declines must not leak its
    probe's host decline into the counters — only the planned-side
    attempt's outcome lands, so one join counts exactly one decision.
    The tracing counters must agree: an uncommitted probe's declines
    leave no phantom device.host_fallback/step_aside trace either."""
    from ballista_tpu.utils import tracing

    rng = np.random.default_rng(17)
    # planned build: unique keys, > 4x the probe -> the swap triggers;
    # swapped build (= the probe) has 20 hot keys x 300 — multiplicity
    # past the top tier AND too many distinct hot keys to split, so the
    # swapped ladder declines and the planned sides run on device
    build = pa.table({"bk": pa.array(np.arange(25_000), type=pa.int64())})
    pk = np.repeat(np.arange(20, dtype=np.int64), 300)
    rng.shuffle(pk)
    probe = pa.table({"pk": pa.array(pk, type=pa.int64())})
    routing_stats(reset=True)
    join_path_stats(reset=True)
    trace_before = tracing.counters()
    res = try_device_inner_join(build, probe, ["bk"], ["pk"], config=cm)
    assert res is not None
    bi, pi = join_indices(np.arange(25_000), pk, "inner")
    np.testing.assert_array_equal(res[0], bi)
    np.testing.assert_array_equal(res[1], pi)
    s = routing_stats(reset=True)
    assert s["engines"] == {"device": 1}
    assert "join_build_swapped" not in s["events"]
    assert join_path_stats(reset=True)["paths"] == {"device": 1}
    trace_after = tracing.counters()
    for name in ("device.host_fallback", "device.step_aside"):
        assert trace_after.get(name, 0) == trace_before.get(name, 0), name


# -- runtime re-planning: general skew handler -------------------------------

def test_skew_split_plan_units():
    from ballista_tpu.ops.stage import SKEW_MAX_DOMINANT, skew_split_plan

    # one monster group among small tails: split exactly the monster
    codes = np.sort(np.concatenate([
        np.arange(3000), np.full(2049, 1500),
    ])).astype(np.int64)
    plan = skew_split_plan(codes, 3000)
    assert plan is not None
    L1, n_dom = plan
    assert n_dom == 1 and L1 <= 16  # tail runs are 1-2 rows
    # uniformly huge groups: nothing to split, not skew
    broad = np.repeat(np.arange(66, dtype=np.int64), 17_000)
    assert skew_split_plan(broad, 66) is None
    assert skew_split_plan(np.zeros(10, dtype=np.int64), 1) is None


def _skewed_topk_table(seed=17, n_small=3000, monster=2049):
    rng = np.random.default_rng(seed)
    g = np.concatenate([np.arange(n_small), np.full(monster, n_small)])
    return pa.table({
        "g": pa.array(g, type=pa.int64()),
        "v": pa.array(rng.uniform(-1e9, 1e9, len(g))
                      + rng.uniform(0, 1e-6, len(g))),
    })


@pytest.mark.parametrize("model", ["true", "false"])
def test_skew_replan_e2e_bit_equality(tmp_path, model):
    """q10's monster-group shape through the engine: with the cost model
    on, the failed one-chunk cover re-plans to the tail cover + in-program
    segment fold (skew_replan recorded); off keeps the default chunking.
    Bit-equal to the host either way."""
    _fresh()
    t = _skewed_topk_table()
    path = str(tmp_path / "t.parquet")
    pq.write_table(t, path)
    out = {}
    for backend in ("tpu", "cpu"):
        ctx = ExecutionContext(BallistaConfig({
            "ballista.executor.backend": backend,
            "ballista.tpu.cost_model": model,
        }))
        ctx.register_parquet("t", path)
        sql = ("select g, min(v) mn, max(v) mx, count(*) c from t "
               "group by g order by mn, g limit 15")
        out[backend] = ctx.sql(sql).collect()
    got, want = out["tpu"].to_pydict(), out["cpu"].to_pydict()
    assert got["g"] == want["g"] and got["c"] == want["c"]
    for col in ("mn", "mx"):
        for a, b in zip(got[col], want[col]):
            assert np.float64(a).tobytes() == np.float64(b).tobytes()
    replans = routing_stats(reset=True)["events"].get("skew_replan", 0)
    if model == "true":
        assert replans >= 1
    else:
        assert replans == 0
    _fresh()


# -- chunked double-buffered h2d upload --------------------------------------

def test_upload_array_chunked_bit_identity(cm, monkeypatch):
    import jax.numpy as jnp

    from ballista_tpu.ops import runtime

    monkeypatch.setattr(runtime, "_H2D_MIN_CHUNKED", 1 << 12)
    monkeypatch.setattr(runtime, "_H2D_CHUNK_BYTES", 1 << 10)
    arr = np.arange(4096, dtype=np.int64).reshape(512, 8)
    routing_stats(reset=True)
    up = runtime.upload_array(arr)
    np.testing.assert_array_equal(np.asarray(up), np.asarray(jnp.asarray(arr)))
    assert routing_stats(reset=True)["events"].get("h2d_chunked") == 1
    # per-chunk timings landed in the cost store as h2d observations
    h2d = [k for k in costmodel.snapshot() if k.startswith("h2d|")]
    assert h2d, "chunked upload recorded no h2d observations"
    # small arrays keep the plain single dispatch
    small = np.arange(16, dtype=np.int64)
    routing_stats(reset=True)
    np.testing.assert_array_equal(np.asarray(runtime.upload_array(small)),
                                  small)
    assert not routing_stats(reset=True)["events"].get("h2d_chunked")
    # cost model OFF restores the single-put path exactly (no chunk copy,
    # no transient HBM peak), whatever the array size
    costmodel.reset(clear_dir=True)
    routing_stats(reset=True)
    np.testing.assert_array_equal(np.asarray(runtime.upload_array(arr)), arr)
    assert not routing_stats(reset=True)["events"].get("h2d_chunked")


def test_h2d_chunk_size_tuned_from_observed_rates(cm, monkeypatch):
    """ISSUE 13 satellite (PR 10 residue): the per-chunk h2d transfer size
    follows the cost store's observed per-bucket rates — the best warm
    bucket wins, a cold store keeps the static default — and the pick is
    surfaced as h2d_chunk_bytes in routing stats. Bit-identical by
    construction (chunking never changes the concatenated bytes)."""
    import jax.numpy as jnp

    from ballista_tpu.ops import runtime

    monkeypatch.setattr(runtime, "_H2D_MIN_CHUNKED", 1 << 12)
    monkeypatch.setattr(runtime, "_H2D_CHUNK_BYTES", 1 << 10)
    monkeypatch.setattr(runtime, "_H2D_CHUNK_CANDIDATES", (1 << 9, 1 << 11))
    # cold store: the static default stands
    assert runtime._h2d_chunk_bytes() == 1 << 10
    # warm rates: the 2 KiB bucket observed much faster per byte
    costmodel.seed("h2d", float(1 << 9), 1.0)
    costmodel.seed("h2d", float(1 << 11), 0.1)
    assert runtime._h2d_chunk_bytes() == 1 << 11
    arr = np.arange(8192, dtype=np.int64).reshape(1024, 8)
    routing_stats(reset=True)
    up = runtime.upload_array(arr)
    np.testing.assert_array_equal(np.asarray(up), np.asarray(jnp.asarray(arr)))
    rs = routing_stats(reset=True)
    assert rs["events"].get("h2d_chunked") == 1
    assert rs["h2d_chunk_bytes"] == 1 << 11
    # flipping the observed rates flips the pick
    costmodel.seed("h2d", float(1 << 9), 0.001)
    assert runtime._h2d_chunk_bytes() == 1 << 9
    # a bucket below MIN_OBSERVATIONS never competes, however fast it looks
    costmodel.seed("h2d", float(1 << 9), 1000.0)        # warm but terrible
    costmodel.seed("h2d", float(1 << 11), 0.0001, n=1)  # fast but unproven
    assert runtime._h2d_chunk_bytes() == 1 << 9


# -- AOT disk tier for the device-join programs (PR 8 residue) ---------------

def test_join_programs_aot_disk_tier(tmp_path):
    """The runs kernel + gather program reload from the AOT disk tier in a
    cold process (compile_hit_disk, zero fresh traces), bit-identically."""
    from ballista_tpu.ops import aotcache
    from ballista_tpu.ops import join as jmod
    from ballista_tpu.ops.runtime import serving_stats

    aotcache.reset(clear_disk_dir=True)
    aotcache.configure(BallistaConfig({
        "ballista.tpu.aot_cache": str(tmp_path / "aot"),
    }))
    jmod._runs_kernel.cache_clear()
    jmod._gather_kernel.cache_clear()
    build = np.repeat(np.arange(50, dtype=np.int64), 3)
    probe = np.arange(-5, 60, dtype=np.int64)
    serving_stats(reset=True)
    first = device_join_indices(build, probe)
    s = serving_stats(reset=True)
    assert s.get("compile_trace", 0) >= 2  # runs + gather traced fresh
    assert s.get("aot_saved", 0) >= 2
    # cold process: fresh wrappers + empty memory map -> disk hits
    aotcache.reset()
    jmod._runs_kernel.cache_clear()
    jmod._gather_kernel.cache_clear()
    second = device_join_indices(build, probe)
    s = serving_stats(reset=True)
    assert s.get("compile_hit_disk", 0) >= 2, s
    assert not s.get("compile_trace"), s
    for a, b in zip(first, second):
        np.testing.assert_array_equal(a, b)
    aotcache.reset(clear_disk_dir=True)
    aotcache.configure(BallistaConfig({}))


# -- adversarial store entries never change results --------------------------

def test_adversarial_store_entries_bit_identity(cm):
    """A poisoned store (absurd rates both directions) may mis-route, but
    every route is bit-identical to the oracle — the invariant the fuzz
    slice sweeps at scale."""
    build, probe = _skewed_join(monster_mult=TOP_TIER + 100)
    for dev_s, host_s in ((1e-12, 100.0), (100.0, 1e-12)):
        costmodel.reset()
        costmodel.configure(cm)
        probe_slots = bucket_rows(len(probe), 16)
        costmodel.seed("join.gather",
                       probe_slots * JOIN_EXTENDED_TIERS[0], dev_s)
        costmodel.seed("join.host", len(build) + len(probe), host_s,
                       engine="host")
        res = device_join_indices(build, probe, config=cm)
        if res is not None:
            _assert_oracle_equal(res, build, probe)
        else:
            # declined to host: the caller's host join IS the oracle
            pass

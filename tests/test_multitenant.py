"""Multi-tenant serving (ISSUE 7): admission control, the plan-fingerprint
result cache, and cross-job artifact sharing.

Three layers, mirroring the subsystem's spread:

- fingerprint units (scheduler/fingerprint.py): the "fully file-backed
  identity" rule applied to whole queries — mtime invalidation by key
  construction, tenant-setting exclusion, unkeyable plans refuse;
- SchedulerState units: durable tenant records, weighted fair-share
  candidate ordering, per-tenant in-flight quotas (the starvation bound),
  result-cache put/lookup/invalidate incl. the chaos-armed put;
- end-to-end standalone-cluster runs: a repeated query served from the
  cache with ZERO executor tasks (counter-asserted), mtime invalidation,
  cache+tenancy surviving a scheduler restart, lost cached partitions
  resubmitting transparently, and seeded chaos on cache.put /
  scheduler.admit staying bit-identical to fault-free.
"""

import logging
import os
import threading
import time

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from ballista_tpu.client import BallistaContext
from ballista_tpu.config import BallistaConfig
from ballista_tpu.executor.runtime import StandaloneCluster
from ballista_tpu.ops.runtime import recovery_stats, tenancy_stats
from ballista_tpu.proto import ballista_pb2 as pb
from ballista_tpu.scheduler.fingerprint import plan_fingerprint
from ballista_tpu.scheduler.kv import MemoryBackend, SqliteBackend
from ballista_tpu.scheduler.state import SchedulerState

logging.getLogger("ballista.executor").setLevel(logging.CRITICAL)


# ---------------------------------------------------------------------------
# fingerprint units
# ---------------------------------------------------------------------------


def _file_plan(path):
    from ballista_tpu.engine import ExecutionContext

    ctx = ExecutionContext()
    ctx.register_parquet("t", path)
    return ctx.sql("select k, sum(v) as s from t group by k order by k"), ctx


@pytest.fixture()
def parquet_file(tmp_path):
    p = str(tmp_path / "t.parquet")
    pq.write_table(
        pa.table({"k": [1, 2, 1, 3], "v": [1.0, 2.0, 3.0, 4.0]}), p
    )
    return p


def test_fingerprint_stable_and_mtime_keyed(parquet_file):
    df, _ = _file_plan(parquet_file)
    plan = df.logical_plan()
    fp1 = plan_fingerprint(plan, {})
    fp2 = plan_fingerprint(plan, {})
    assert fp1 is not None and fp1 == fp2
    # touching the input changes the RESULT key but not the CONTENT key
    # (planning depends on the file list, results on the file bytes)
    os.utime(parquet_file, (time.time() + 5, time.time() + 5))
    fp3 = plan_fingerprint(plan, {})
    assert fp3 is not None
    assert fp3[0] == fp1[0] and fp3[1] != fp1[1]


def test_fingerprint_settings_participate_tenant_excluded(parquet_file):
    df, _ = _file_plan(parquet_file)
    plan = df.logical_plan()
    base = plan_fingerprint(plan, {})
    # result-affecting settings change both keys...
    other = plan_fingerprint(plan, {"ballista.executor.backend": "tpu"})
    assert other is not None and other[0] != base[0] and other[1] != base[1]
    # ...tenancy settings change neither (tenants SHARE cache lines)
    tenanted = plan_fingerprint(
        plan, {"ballista.tenant.name": "alice", "ballista.tenant.priority": "7"}
    )
    assert tenanted == base


def test_fingerprint_memory_tables_content_keyed():
    from ballista_tpu.engine import ExecutionContext

    ctx = ExecutionContext()
    ctx.register_record_batches("m", pa.table({"x": [1, 2, 3]}))
    p1 = plan_fingerprint(ctx.sql("select sum(x) as s from m").logical_plan(), {})
    ctx2 = ExecutionContext()
    ctx2.register_record_batches("m", pa.table({"x": [1, 2, 3]}))
    p2 = plan_fingerprint(ctx2.sql("select sum(x) as s from m").logical_plan(), {})
    assert p1 is not None and p1 == p2  # same content, same identity
    ctx3 = ExecutionContext()
    ctx3.register_record_batches("m", pa.table({"x": [1, 2, 4]}))
    p3 = plan_fingerprint(ctx3.sql("select sum(x) as s from m").logical_plan(), {})
    assert p3 is not None and p3 != p1  # different content, different key


def test_fingerprint_volatile_function_unkeyable(parquet_file):
    """now() makes results depend on WHEN the query runs: such plans must
    never cache (a cached now() comparison would be frozen at the first
    run's clock forever)."""
    from ballista_tpu.engine import ExecutionContext

    ctx = ExecutionContext()
    ctx.register_parquet("t", parquet_file)
    volatile = ctx.sql(
        "select count(*) as n from t where now() > to_timestamp('2000-01-01')"
    ).logical_plan()
    assert plan_fingerprint(volatile, {}) is None
    stable = ctx.sql("select count(*) as n from t").logical_plan()
    assert plan_fingerprint(stable, {}) is not None


def test_fingerprint_missing_file_unkeyable(parquet_file):
    df, _ = _file_plan(parquet_file)
    plan = df.logical_plan()
    assert plan_fingerprint(plan, {}) is not None
    os.unlink(parquet_file)
    assert plan_fingerprint(plan, {}) is None


# ---------------------------------------------------------------------------
# SchedulerState units: tenancy + admission
# ---------------------------------------------------------------------------


def _meta(i, host="h", port=50051):
    return pb.ExecutorMetadata(id=i, host=host, port=port)


def _pending(job, stage, part):
    t = pb.TaskStatus()
    t.partition_id.job_id = job
    t.partition_id.stage_id = stage
    t.partition_id.partition_id = part
    return t


def _running(job, stage, part, executor="e1"):
    t = _pending(job, stage, part)
    t.running.executor_id = executor
    return t


def _scan_stage(n_parts=2):
    """A real single-stage plan so assignment can bind it."""
    from ballista_tpu.distributed.planner import DistributedPlanner
    from ballista_tpu.engine import ExecutionContext
    from ballista_tpu.logical import col, functions as F

    ctx = ExecutionContext()
    ctx.register_record_batches(
        "t", pa.table({"g": ["a", "b"], "v": [1.0, 2.0]}), n_partitions=n_parts
    )
    df = ctx.table("t").select(col("g"))
    physical = ctx.create_physical_plan(df.logical_plan())
    stages = DistributedPlanner().plan_query_stages("job", physical)
    return stages[0]


def _seed_job(s, job, tenant, priority=0, n_parts=2, stage=None):
    stage = stage if stage is not None else _scan_stage(n_parts)
    s.save_job_tenant(job, tenant, priority)
    s.save_stage_plan(job, stage.stage_id, stage)
    for p in range(n_parts):
        s.save_task_status(_pending(job, stage.stage_id, p))
    return stage


def test_job_tenant_roundtrip_and_restart_durability():
    kv = MemoryBackend()
    s = SchedulerState(kv, "t")
    s.save_job_tenant("j1", "alice", 3)
    assert s.job_tenant("j1") == ("alice", 3)
    assert s.job_tenant("unknown") == ("", 0)
    # a FRESH state over the same store (scheduler restart) reloads it
    s2 = SchedulerState(kv, "t")
    assert s2.job_tenant("j1") == ("alice", 3)


def test_quota_blocks_saturating_tenant():
    """The starvation bound: tenant A at its in-flight quota is skipped and
    tenant B's task is handed out, even though A's job sorts first."""
    kv = MemoryBackend()
    s = SchedulerState(
        kv, "t", config=BallistaConfig({"ballista.tenant.max_inflight": "2"})
    )
    s.save_executor_metadata(_meta("e1"))
    stage = _scan_stage(4)
    _seed_job(s, "aaaa", "hog", n_parts=4, stage=stage)
    # hog saturates its quota (2 in flight) while alone on the cluster
    a1 = s.assign_next_schedulable_task("e1")
    a2 = s.assign_next_schedulable_task("e1")
    assert a1[0].partition_id.job_id == "aaaa"
    assert a2[0].partition_id.job_id == "aaaa"
    # the light tenant arrives: its task is handed out, hog's remaining
    # pending tasks stay queued behind the quota
    _seed_job(s, "zzzz", "light", n_parts=1, stage=_scan_stage(1))
    a3 = s.assign_next_schedulable_task("e1")
    assert a3 is not None and a3[0].partition_id.job_id == "zzzz"
    # light is done; hog stays blocked until its in-flight drains
    assert s.assign_next_schedulable_task("e1") is None
    done = pb.TaskStatus()
    done.CopyFrom(a1[0])
    done.completed.executor_id = "e1"
    done.completed.path = "/x"
    assert s.accept_task_status(done)
    a4 = s.assign_next_schedulable_task("e1")
    assert a4 is not None and a4[0].partition_id.job_id == "aaaa"
    assert tenancy_stats(reset=True).get("admit_quota_deferred", 0) >= 1


def test_fair_share_prefers_light_tenant():
    """With no quota, the tenant with the smallest in_flight/weight ratio
    is visited first — a busy tenant yields the next slot."""
    kv = MemoryBackend()
    s = SchedulerState(kv, "t")
    s.save_executor_metadata(_meta("e1"))
    _seed_job(s, "aaaa", "busy", n_parts=3, stage=_scan_stage(3))
    _seed_job(s, "zzzz", "idle", n_parts=1, stage=_scan_stage(1))
    a1 = s.assign_next_schedulable_task("e1")
    assert a1[0].partition_id.job_id == "aaaa"  # both idle: name order ties
    # busy now has 1 in flight; idle has 0 -> idle's task goes next even
    # though its job id sorts last
    a2 = s.assign_next_schedulable_task("e1")
    assert a2 is not None and a2[0].partition_id.job_id == "zzzz"
    shares = s.tenant_task_shares()
    assert shares == {"busy": 1, "idle": 1}


def test_weighted_fair_share_ratio():
    """weights alice:4,bob:1 — alice keeps priority until her in-flight is
    4x bob's."""
    kv = MemoryBackend()
    s = SchedulerState(
        kv, "t",
        config=BallistaConfig({"ballista.tenant.weights": "alice:4,bob:1"}),
    )
    s.save_executor_metadata(_meta("e1"))
    _seed_job(s, "aj", "alice", n_parts=6, stage=_scan_stage(6))
    _seed_job(s, "bj", "bob", n_parts=6, stage=_scan_stage(6))
    got = []
    for _ in range(5):
        a = s.assign_next_schedulable_task("e1")
        got.append(s.job_tenant(a[0].partition_id.job_id)[0])
    # 0/4 vs 0/1 ties -> alice (name order); then 1/4 < 0/1 -> ... bob only
    # once alice holds 4x bob's share: a,a,a,a interleaved with bob's first
    assert got.count("alice") == 4 and got.count("bob") == 1, got


def test_priority_orders_jobs_within_tenant():
    kv = MemoryBackend()
    s = SchedulerState(kv, "t")
    s.save_executor_metadata(_meta("e1"))
    _seed_job(s, "aaaa", "alice", priority=0, n_parts=1, stage=_scan_stage(1))
    _seed_job(s, "zzzz", "alice", priority=9, n_parts=1, stage=_scan_stage(1))
    a = s.assign_next_schedulable_task("e1")
    assert a[0].partition_id.job_id == "zzzz"  # high priority first


def test_admission_order_unchanged_without_tenancy():
    """Default config + untenanted jobs reduce to the historical
    (job, str(stage)) candidate order — the PR 2 identity contract."""
    kv = MemoryBackend()
    s = SchedulerState(kv, "t")
    s.save_executor_metadata(_meta("e1"))
    st1 = _scan_stage(1)
    for job in ("jb", "ja", "jc"):
        s.save_stage_plan(job, st1.stage_id, st1)
        s.save_task_status(_pending(job, st1.stage_id, 0))
    picked = [
        s.assign_next_schedulable_task("e1")[0].partition_id.job_id
        for _ in range(3)
    ]
    assert picked == ["ja", "jb", "jc"]


# ---------------------------------------------------------------------------
# SchedulerState units: result cache
# ---------------------------------------------------------------------------


def _completed_job(executor="e1", path="/data/p0"):
    c = pb.CompletedJob()
    pl = c.partition_location.add()
    pl.partition_id.job_id = "j"
    pl.partition_id.stage_id = 1
    pl.executor_meta.CopyFrom(_meta(executor))
    pl.path = path
    return c


def test_result_cache_roundtrip_and_liveness():
    kv = MemoryBackend()
    s = SchedulerState(kv, "t")
    s.save_executor_metadata(_meta("e1"))
    tenancy_stats(reset=True)
    assert s.result_cache_put("f" * 64, _completed_job())
    hit = s.result_cache_lookup("f" * 64)
    assert hit is not None and hit.cached
    assert hit.partition_location[0].path == "/data/p0"
    # entry referencing an executor with no live lease: invalidated on
    # lookup, entry deleted
    assert s.result_cache_put("a" * 64, _completed_job(executor="gone"))
    assert s.result_cache_lookup("a" * 64) is None
    assert kv.get(s._key("resultcache", "a" * 64)) is None
    stats = tenancy_stats(reset=True)
    assert stats.get("cache_hit") == 1
    assert stats.get("cache_invalidated") == 1
    assert stats.get("cache_put") == 2


def test_result_cache_put_chaos_torn():
    """rate=1.0 on cache.put: every publish is torn, recorded, and SKIPPED
    — the completion stands, later lookups just miss."""
    kv = MemoryBackend()
    s = SchedulerState(
        kv, "t",
        config=BallistaConfig({
            "ballista.chaos.rate": "1.0",
            "ballista.chaos.sites": "cache.put",
        }),
    )
    s.save_executor_metadata(_meta("e1"))
    tenancy_stats(reset=True)
    assert not s.result_cache_put("b" * 64, _completed_job())
    assert s.result_cache_lookup("b" * 64) is None
    stats = tenancy_stats(reset=True)
    assert stats.get("cache_put_torn") == 1
    assert not stats.get("cache_put")


# ---------------------------------------------------------------------------
# end-to-end: standalone cluster
# ---------------------------------------------------------------------------


@pytest.fixture()
def tpath(tmp_path):
    p = str(tmp_path / "t.parquet")
    pq.write_table(
        pa.table(
            {
                "k": pa.array([i % 7 for i in range(500)], type=pa.int64()),
                "v": pa.array([float(i) for i in range(500)]),
            }
        ),
        p,
    )
    return p


def _jobs_of(state):
    out = {}
    for k, _v in state.kv.get_prefix(state._key("jobs")):
        job = k.rsplit("/", 1)[1]
        out[job] = state.get_job_metadata(job)
    return out


def test_cache_hit_zero_tasks_and_mtime_invalidation(tpath):
    cluster = StandaloneCluster(n_executors=2)
    try:
        ctx = BallistaContext(
            *cluster.scheduler_addr,
            settings={"ballista.tenant.name": "dash"},
        )
        ctx.register_parquet("t", tpath)
        tenancy_stats(reset=True)
        q = "select k, sum(v) as s from t group by k order by k"
        cold = ctx.sql(q).collect()
        warm = ctx.sql(q).collect()
        assert warm.equals(cold)  # bit-identical to cold execution
        st = cluster.scheduler_impl.state
        cached_jobs = [
            j for j, js in _jobs_of(st).items()
            if js.WhichOneof("status") == "completed" and js.completed.cached
        ]
        assert len(cached_jobs) == 1
        # the acceptance counter: a cache-hit job runs ZERO executor tasks
        assert st.get_job_tasks(cached_jobs[0]) == []
        stats = tenancy_stats(reset=True)
        assert stats.get("cache_hit") == 1 and stats.get("cache_put") == 1
        # touching an input file's mtime invalidates: fresh execution,
        # fresh entry, same bits
        os.utime(tpath, (time.time() + 5, time.time() + 5))
        fresh = ctx.sql(q).collect()
        assert fresh.equals(cold)
        stats = tenancy_stats(reset=True)
        assert stats.get("cache_hit", 0) == 0 and stats.get("cache_put") == 1
        ctx.close()
    finally:
        cluster.shutdown()


def test_cache_and_tenancy_survive_scheduler_restart(tpath):
    """The cache entry, the tenant record, and admission all live in the KV
    — a restarted scheduler on the same store keeps serving hits."""
    kv = SqliteBackend.temporary()
    cluster = StandaloneCluster(n_executors=1, kv=kv)
    try:
        ctx = BallistaContext(
            *cluster.scheduler_addr, settings={"ballista.tenant.name": "dash"}
        )
        ctx.register_parquet("t", tpath)
        q = "select k, count(*) as n from t group by k order by k"
        cold = ctx.sql(q).collect()
        cluster.restart_scheduler()
        tenancy_stats(reset=True)
        warm = ctx.sql(q).collect()
        assert warm.equals(cold)
        assert tenancy_stats(reset=True).get("cache_hit") == 1
        st = cluster.scheduler_impl.state
        cached = [
            j for j, js in _jobs_of(st).items()
            if js.WhichOneof("status") == "completed" and js.completed.cached
        ]
        assert cached and all(st.job_tenant(j)[0] == "dash" for j in cached)
        ctx.close()
    finally:
        cluster.shutdown()


def test_lost_cached_partition_invalidates_and_resubmits(tpath):
    """Cached locations outliving their data (executor died under a live
    lease): the fetch fails, ReportLostPartition invalidates the entry and
    fails the cached job, and collect() resubmits transparently — the
    query still returns the right rows."""
    cluster = StandaloneCluster(n_executors=2)
    try:
        ctx = BallistaContext(*cluster.scheduler_addr)
        ctx.register_parquet("t", tpath)
        q = "select k, sum(v) as s from t group by k order by k"
        cold = ctx.sql(q).collect()
        # kill the result-holding executors' data planes without waiting
        # out the 60s lease (the lazy liveness check must NOT catch this)
        st = cluster.scheduler_impl.state
        completed = [
            js for js in _jobs_of(st).values()
            if js.WhichOneof("status") == "completed"
        ]
        owners = {
            pl.executor_meta.id
            for js in completed
            for pl in js.completed.partition_location
        }
        for ex in cluster.executors:
            if ex.id in owners:
                ex.poll_loop.stop()
                ex.flight.shutdown()
        assert len(owners) < len(cluster.executors), (
            "need a surviving executor to re-execute on"
        )
        tenancy_stats(reset=True)
        again = ctx.sql(q).collect()
        assert again.equals(cold)
        stats = tenancy_stats(reset=True)
        assert stats.get("cache_hit") == 1  # served stale, then...
        assert stats.get("cache_invalidated", 0) >= 1  # ...invalidated
        assert stats.get("cache_lost_resubmitted") == 1  # ...and resubmitted
        ctx.close()
    finally:
        cluster.shutdown()


def test_starvation_quota_end_to_end(tpath):
    """A saturating tenant cannot block another tenant's job past its
    quota: both jobs complete, and the light tenant's tasks were assigned
    while the hog still had pending work (its share stays bounded)."""
    cluster = StandaloneCluster(
        n_executors=1,
        config=BallistaConfig({"ballista.tenant.max_inflight": "1"}),
        concurrent_tasks=1,
    )
    try:
        hog = BallistaContext(
            *cluster.scheduler_addr,
            settings={
                "ballista.tenant.name": "hog",
                "ballista.shuffle.partitions": "8",
                # distinct per-tenant settings also prove cache isolation
                # is NOT needed for correctness here: different settings,
                # different fingerprints
            },
        )
        light = BallistaContext(
            *cluster.scheduler_addr,
            settings={"ballista.tenant.name": "light"},
        )
        for c in (hog, light):
            c.register_parquet("t", tpath)
        big = "select k, v, count(*) as n from t group by k, v order by k, v limit 5"
        small = "select count(*) as n from t"
        results = {}
        errors = []

        def run(name, c, sql):
            try:
                results[name] = c.sql(sql).collect()
            except Exception as e:  # surface in the main thread
                errors.append((name, e))

        th = threading.Thread(target=run, args=("hog", hog, big))
        tl = threading.Thread(target=run, args=("light", light, small))
        th.start()
        tl.start()
        th.join(120)
        tl.join(120)
        assert not errors, errors
        assert results["light"].column("n").to_pylist() == [500]
        assert results["hog"].num_rows == 5
        shares = cluster.scheduler_impl.state.tenant_task_shares()
        assert shares.get("hog", 0) >= 1 and shares.get("light", 0) >= 1
        hog.close()
        light.close()
    finally:
        cluster.shutdown()


def _admit_seed(rate=0.35):
    """A seed whose FIRST admission verdict injects (deterministic scan,
    like the chaos suite's seed picks)."""
    from ballista_tpu.utils.chaos import ChaosInjector

    for seed in range(200):
        inj = ChaosInjector(seed, rate, ["scheduler.admit"])
        if inj.should_inject("scheduler.admit", "admit1"):
            return seed
    raise AssertionError("no injecting seed in range")


def test_admit_chaos_bit_identical(tpath):
    """Seeded chaos on scheduler.admit: the faulted PollWork aborts before
    the Running flip, the executor retries, and the run stays bit-identical
    to fault-free."""
    q = "select k, sum(v) as s, count(*) as n from t group by k order by k"
    outs = {}
    for chaos in (False, True):
        cfg = None
        if chaos:
            cfg = BallistaConfig({
                "ballista.chaos.rate": "0.35",
                "ballista.chaos.seed": str(_admit_seed()),
                "ballista.chaos.sites": "scheduler.admit",
            })
        cluster = StandaloneCluster(n_executors=2, config=cfg)
        try:
            ctx = BallistaContext(*cluster.scheduler_addr)
            ctx.register_parquet("t", tpath)
            recovery_stats(reset=True)
            outs[chaos] = ctx.sql(q).collect()
            if chaos:
                assert recovery_stats(reset=True).get("chaos_injected", 0) > 0
            ctx.close()
        finally:
            cluster.shutdown()
    assert outs[True].equals(outs[False])


def test_cache_put_chaos_bit_identical(tpath):
    """rate=1.0 on cache.put cluster-wide: every publish torn, zero hits,
    every repeat re-executes — and the results stay bit-identical."""
    cluster = StandaloneCluster(
        n_executors=2,
        config=BallistaConfig({
            "ballista.chaos.rate": "1.0",
            "ballista.chaos.sites": "cache.put",
        }),
    )
    try:
        ctx = BallistaContext(*cluster.scheduler_addr)
        ctx.register_parquet("t", tpath)
        tenancy_stats(reset=True)
        q = "select k, sum(v) as s from t group by k order by k"
        a = ctx.sql(q).collect()
        b = ctx.sql(q).collect()
        assert a.equals(b)
        stats = tenancy_stats(reset=True)
        assert stats.get("cache_put_torn", 0) >= 2
        assert stats.get("cache_hit", 0) == 0
        ctx.close()
    finally:
        cluster.shutdown()


_CRASH_RATE = 0.05


def _crash_seed():
    """A seed that crashes the FIRST scheduler life early (g0, accepted
    status 1-4: while the first job's tasks are being admitted/executed)
    and lets the restarted life (g1) survive the whole run's status
    horizon — the deterministic-scan idiom from test_scheduler_restart."""
    from ballista_tpu.utils.chaos import ChaosInjector

    for seed in range(20000):
        inj = ChaosInjector(seed, _CRASH_RATE, ["scheduler.crash"])

        def fires_at(gen, horizon):
            for n in range(1, horizon):
                if inj.should_inject("scheduler.crash", f"g{gen}/status{n}"):
                    return n
            return None

        if fires_at(0, 5) is not None and fires_at(1, 120) is None:
            return seed
    raise AssertionError("no suitable crash seed in range")


def test_scheduler_crash_mid_admission_bit_identical(tmp_path):
    """ISSUE 7 acceptance: a seeded scheduler crash while a tenanted job is
    being admitted/executed, restarted on the same durable store, stays
    bit-identical to fault-free — and the repeated query afterwards is
    served from the (durable) result cache."""
    # a 2-file table: the scan gets 2 partitions, so the job is a real
    # 2-stage plan with enough task statuses for the seeded crash to land
    # mid-execution (a 1-partition scan collapses to a single task)
    tdir = tmp_path / "t"
    tdir.mkdir()
    for i in range(2):
        pq.write_table(
            pa.table({
                "k": pa.array([j % 7 for j in range(250)], type=pa.int64()),
                "v": pa.array([float(j + i * 250) for j in range(250)]),
            }),
            str(tdir / f"part{i}.parquet"),
        )
    tpath = str(tdir)
    q = "select k, sum(v) as s, count(*) as n from t group by k order by k"

    clean_cluster = StandaloneCluster(n_executors=2)
    try:
        cctx = BallistaContext(*clean_cluster.scheduler_addr)
        cctx.register_parquet("t", tpath)
        clean = cctx.sql(q).collect()
        cctx.close()
    finally:
        clean_cluster.shutdown()

    cluster = StandaloneCluster(
        n_executors=2,
        kv=SqliteBackend(str(tmp_path / "sched.db")),
        config=BallistaConfig({
            "ballista.chaos.rate": str(_CRASH_RATE),
            "ballista.chaos.seed": str(_crash_seed()),
            "ballista.chaos.sites": "scheduler.crash",
            "ballista.rpc.retries": "20",
            "ballista.rpc.backoff_ms": "50",
        }),
    )
    stop = threading.Event()

    def supervisor():
        while not stop.is_set():
            if cluster.scheduler_impl.crashed:
                cluster.restart_scheduler()
            time.sleep(0.02)

    sup = threading.Thread(target=supervisor, daemon=True)
    sup.start()
    try:
        ctx = BallistaContext(
            *cluster.scheduler_addr,
            settings={
                "ballista.tenant.name": "dash",
                "ballista.rpc.retries": "20",
            },
        )
        ctx.register_parquet("t", tpath)
        recovery_stats(reset=True)
        tenancy_stats(reset=True)
        first = ctx.sql(q).collect()
        second = ctx.sql(q).collect()
        ctx.close()
    finally:
        stop.set()
        sup.join(timeout=5)
        cluster.shutdown()
    assert first.equals(clean) and second.equals(clean)
    stats = recovery_stats(reset=True)
    assert stats.get("chaos_scheduler_crash", 0) >= 1, stats
    assert stats.get("scheduler_restart", 0) >= 1, stats
    # the repeat rode the durable cache entry written after the restart
    assert tenancy_stats(reset=True).get("cache_hit", 0) >= 1


def test_plan_cache_shares_physical_plans(tpath):
    """Cross-job artifact sharing: with the result cache off (forcing the
    second submission to really plan + execute), the second identical query
    reuses the first's physical plan — and the results agree."""
    cluster = StandaloneCluster(n_executors=2)
    try:
        ctx = BallistaContext(
            *cluster.scheduler_addr,
            settings={"ballista.cache.results": "false"},
        )
        ctx.register_parquet("t", tpath)
        tenancy_stats(reset=True)
        q = "select k, max(v) as m from t group by k order by k"
        a = ctx.sql(q).collect()
        b = ctx.sql(q).collect()
        assert a.equals(b)
        stats = tenancy_stats(reset=True)
        assert stats.get("plan_cache_hit") == 1
        assert stats.get("cache_hit", 0) == 0
        ctx.close()
    finally:
        cluster.shutdown()


def test_cross_tenant_cache_sharing(tpath):
    """N tenants running the same dashboard query execute it once: the
    fingerprint excludes tenant identity, so tenant B hits tenant A's
    entry."""
    cluster = StandaloneCluster(n_executors=2)
    try:
        q = "select k, sum(v) as s from t group by k order by k"
        outs = []
        tenancy_stats(reset=True)
        for tenant in ("alice", "bob", "carol"):
            ctx = BallistaContext(
                *cluster.scheduler_addr,
                settings={"ballista.tenant.name": tenant},
            )
            ctx.register_parquet("t", tpath)
            outs.append(ctx.sql(q).collect())
            ctx.close()
        assert outs[0].equals(outs[1]) and outs[1].equals(outs[2])
        stats = tenancy_stats(reset=True)
        assert stats.get("cache_hit") == 2  # bob and carol rode alice's run
        assert stats.get("cache_put") == 1
    finally:
        cluster.shutdown()

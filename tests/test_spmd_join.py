"""SPMD co-partitioned join (parallel/spmd_join.py): the hash-repartition
exchange runs as lax.all_to_all inside ONE mesh program on the 8-device CPU
mesh; per-shard sort+searchsorted matching; host assembles matched row-id
pairs. Replaces the reference's two materialized shuffles feeding a
partitioned join (SURVEY §2.8 RepartitionExec -> all_to_all mapping)."""

import numpy as np
import pyarrow as pa
import pytest

from ballista_tpu.config import BallistaConfig
from ballista_tpu.distributed.planner import DistributedPlanner
from ballista_tpu.engine import ExecutionContext
from ballista_tpu.parallel.spmd_join import SpmdJoinExec
from ballista_tpu.physical.plan import TaskContext

SPMD_SETTINGS = {
    "ballista.executor.backend": "tpu",
    "ballista.tpu.spmd_stages": "true",
    "ballista.tpu.mesh": "data:8",
}


def _dim(n=500, seed=1):
    """Unique-keyed build side with awkward payload types."""
    rng = np.random.default_rng(seed)
    keys = np.arange(n, dtype=np.int64)
    rng.shuffle(keys)
    name = pa.array(
        [None if i % 97 == 0 else f"dim-{i}" for i in range(n)],
        type=pa.string(),
    )
    return pa.table(
        {
            "dk": pa.array(keys),
            "name": name,
            "weight": pa.array(rng.uniform(0, 1, n)),  # float64 payload
        }
    )


def _fact(n=6000, nk=700, seed=2):
    """Probe side: keys beyond the dim range stay unmatched; some null."""
    rng = np.random.default_rng(seed)
    fk = rng.integers(0, nk, n)
    fk_arr = pa.array(
        [None if i % 143 == 0 else int(v) for i, v in enumerate(fk)],
        type=pa.int64(),
    )
    return pa.table(
        {
            "fk": fk_arr,
            "amount": pa.array(rng.uniform(-50, 50, n)),
            "tag": pa.array([f"t{i % 13}" for i in range(n)]),
        }
    )


def _find_join(stages):
    def find(n):
        if isinstance(n, SpmdJoinExec):
            return n
        for c in n.children():
            r = find(c)
            if r is not None:
                return r
        return None

    return next((j for j in (find(s) for s in stages) if j is not None), None)


def _plan_join(left, right, lk, rk, how, settings=SPMD_SETTINGS,
               nl=3, nr=4):
    cfg = BallistaConfig(settings)
    ctx = ExecutionContext(cfg)
    ctx.register_record_batches("l", left, n_partitions=nl)
    ctx.register_record_batches("r", right, n_partitions=nr)
    df = ctx.table("l").join(ctx.table("r"), lk, rk, how=how)
    phys = ctx.create_physical_plan(df.logical_plan())
    stages = DistributedPlanner(cfg).plan_query_stages("job", phys)
    return _find_join(stages), cfg


def _host_oracle(left, right, lk, rk, how):
    cfg = BallistaConfig({"ballista.executor.backend": "cpu"})
    ctx = ExecutionContext(cfg)
    ctx.register_record_batches("l", left, n_partitions=1)
    ctx.register_record_batches("r", right, n_partitions=1)
    return (
        ctx.table("l").join(ctx.table("r"), lk, rk, how=how).collect()
    )


def _canon(table, keys):
    """Order-insensitive comparison form."""
    return table.sort_by([(k, "ascending") for k in keys]).to_pydict()


def test_inner_join_mesh_matches_host():
    dim, fact = _dim(), _fact()
    spmd, cfg = _plan_join(dim, fact, ["dk"], ["fk"], "inner")
    assert spmd is not None, "planner did not fuse the join"
    tctx = TaskContext(config=cfg, work_dir="/tmp", job_id="t")
    out = pa.Table.from_batches(list(spmd.execute(0, tctx)))
    assert spmd.last_path == "mesh", "mesh path did not run"

    oracle = _host_oracle(dim, fact, ["dk"], ["fk"], "inner")
    assert out.num_rows == oracle.num_rows
    assert _canon(out, ["dk", "amount"]) == _canon(oracle, ["dk", "amount"])


def test_left_join_mesh_matches_host():
    # fact keys cover only 0..299 of dim's 0..499: ~200 dim rows unmatched
    dim, fact = _dim(), _fact(nk=300)
    spmd, cfg = _plan_join(dim, fact, ["dk"], ["fk"], "left")
    assert spmd is not None
    tctx = TaskContext(config=cfg, work_dir="/tmp", job_id="t")
    out = pa.Table.from_batches(list(spmd.execute(0, tctx)))
    assert spmd.last_path == "mesh"

    oracle = _host_oracle(dim, fact, ["dk"], ["fk"], "left")
    assert out.num_rows == oracle.num_rows
    # unmatched dim rows carry nulls on the fact side
    n_null = sum(1 for v in out.column("amount").to_pylist() if v is None)
    n_null_o = sum(1 for v in oracle.column("amount").to_pylist() if v is None)
    assert n_null == n_null_o > 0
    assert _canon(out, ["dk", "amount"]) == _canon(oracle, ["dk", "amount"])


def test_string_and_composite_keys():
    n = 300
    left = pa.table(
        {
            "c1": pa.array([f"g{i % 20}" for i in range(n)]),
            "c2": pa.array(np.arange(n, dtype=np.int64) % 15),
            "lv": pa.array(np.arange(n, dtype=np.int64)),
        }
    )
    # unique composite build key (c1, c2) requires n <= 20*15
    left = left.group_by(["c1", "c2"]).aggregate([("lv", "max")])
    right = pa.table(
        {
            "k1": pa.array([f"g{i % 23}" for i in range(900)]),
            "k2": pa.array(np.arange(900, dtype=np.int64) % 17),
            "rv": pa.array(np.random.default_rng(0).uniform(0, 1, 900)),
        }
    )
    spmd, cfg = _plan_join(left, right, ["c1", "c2"], ["k1", "k2"], "inner")
    assert spmd is not None
    tctx = TaskContext(config=cfg, work_dir="/tmp", job_id="t")
    out = pa.Table.from_batches(list(spmd.execute(0, tctx)))
    assert spmd.last_path == "mesh"
    oracle = _host_oracle(left, right, ["c1", "c2"], ["k1", "k2"], "inner")
    assert _canon(out, ["c1", "c2", "rv"]) == _canon(oracle, ["c1", "c2", "rv"])


def test_duplicate_build_keys_run_on_mesh():
    """Many-many joins run ON the mesh now: paired searchsorted run-lengths
    + bounded-width gather expand every duplicate match."""
    left = pa.table(
        {
            "dk": pa.array([1, 2, 2, 3], type=pa.int64()),
            "name": pa.array(["a", "b", "c", "d"]),
        }
    )
    right = pa.table(
        {
            "fk": pa.array([2, 3, 4, 2], type=pa.int64()),
            "amount": pa.array([1.0, 2.0, 3.0, 4.0]),
        }
    )
    spmd, cfg = _plan_join(left, right, ["dk"], ["fk"], "inner", nl=1, nr=2)
    assert spmd is not None
    tctx = TaskContext(config=cfg, work_dir="/tmp", job_id="t")
    out = pa.Table.from_batches(list(spmd.execute(0, tctx)))
    assert spmd.last_path == "mesh", "duplicate build keys must not decline"
    oracle = _host_oracle(left, right, ["dk"], ["fk"], "inner")
    assert out.num_rows == oracle.num_rows == 5  # 2x2 + 1 match expansion
    assert _canon(out, ["dk", "amount"]) == _canon(oracle, ["dk", "amount"])


def test_duplicate_build_keys_left_join_on_mesh():
    """LEFT join with duplicate build keys: the matched-left bitmap must be
    duplicate-safe (every copy of a matched key counts as matched; unmatched
    build rows null-pad exactly once)."""
    rng = np.random.default_rng(7)
    n = 400
    left = pa.table(
        {
            "dk": pa.array(rng.integers(0, 60, n), type=pa.int64()),
            "name": pa.array([f"d{i}" for i in range(n)]),
        }
    )
    right = pa.table(
        {
            "fk": pa.array(rng.integers(0, 40, 900), type=pa.int64()),
            "amount": pa.array(rng.uniform(-5, 5, 900)),
        }
    )
    spmd, cfg = _plan_join(left, right, ["dk"], ["fk"], "left")
    assert spmd is not None
    tctx = TaskContext(config=cfg, work_dir="/tmp", job_id="t")
    out = pa.Table.from_batches(list(spmd.execute(0, tctx)))
    assert spmd.last_path == "mesh"
    oracle = _host_oracle(left, right, ["dk"], ["fk"], "left")
    assert out.num_rows == oracle.num_rows
    assert _canon(out, ["dk", "name", "amount"]) == _canon(
        oracle, ["dk", "name", "amount"]
    )


def test_multiplicity_past_top_tier_steps_aside():
    """A monster build key beyond JOIN_MULTIPLICITY_TIERS[-1] declines with
    a recorded reason and joins INLINE over the already-collected sides (no
    subplan re-execution, no shuffle materialization) — never wrong rows."""
    from ballista_tpu.ops.kernels import JOIN_MULTIPLICITY_TIERS
    from ballista_tpu.ops.runtime import join_path_stats

    mult = JOIN_MULTIPLICITY_TIERS[-1] + 10
    left = pa.table(
        {
            "dk": pa.array([7] * mult + [1, 2], type=pa.int64()),
            "name": pa.array([f"d{i}" for i in range(mult + 2)]),
        }
    )
    right = pa.table(
        {
            "fk": pa.array([7, 1, 9], type=pa.int64()),
            "amount": pa.array([1.0, 2.0, 3.0]),
        }
    )
    spmd, cfg = _plan_join(left, right, ["dk"], ["fk"], "inner", nl=1, nr=2)
    assert spmd is not None
    join_path_stats(reset=True)
    tctx = TaskContext(config=cfg, work_dir="/tmp", job_id="t")
    out = pa.Table.from_batches(list(spmd.execute(0, tctx)))
    assert spmd.last_path == "host-inline"
    stats = join_path_stats(reset=True)
    assert stats["paths"].get("step_aside") == 1
    assert any("multiplicity" in r for r in stats["reasons"])
    oracle = _host_oracle(left, right, ["dk"], ["fk"], "inner")
    assert out.num_rows == oracle.num_rows == mult + 1
    assert _canon(out, ["dk", "amount"]) == _canon(oracle, ["dk", "amount"])


def test_serde_roundtrip():
    from ballista_tpu.serde.physical import (
        phys_plan_from_proto,
        phys_plan_to_proto,
    )

    dim, fact = _dim(100), _fact(400, nk=120)
    spmd, cfg = _plan_join(dim, fact, ["dk"], ["fk"], "left")
    assert spmd is not None
    back = phys_plan_from_proto(phys_plan_to_proto(spmd))
    assert isinstance(back, SpmdJoinExec)
    assert back.schema() == spmd.schema()
    assert back.subplan.partitioned == spmd.subplan.partitioned
    # the deserialized operator executes (mesh or host, same rows)
    tctx = TaskContext(config=cfg, work_dir="/tmp", job_id="t")
    out = pa.Table.from_batches(list(back.execute(0, tctx)))
    oracle = _host_oracle(dim, fact, ["dk"], ["fk"], "left")
    assert out.num_rows == oracle.num_rows


def test_cpu_backend_uses_host_path():
    dim, fact = _dim(50), _fact(200, nk=60)
    settings = dict(SPMD_SETTINGS, **{"ballista.executor.backend": "cpu"})
    spmd, cfg = _plan_join(dim, fact, ["dk"], ["fk"], "inner",
                           settings=settings)
    assert spmd is not None
    tctx = TaskContext(config=cfg, work_dir="/tmp", job_id="t")
    out = pa.Table.from_batches(list(spmd.execute(0, tctx)))
    oracle = _host_oracle(dim, fact, ["dk"], ["fk"], "inner")
    assert _canon(out, ["dk", "amount"]) == _canon(oracle, ["dk", "amount"])


def test_refactorize_preserves_null_sentinel():
    """Composite keys whose packed cardinality exceeds 2^31 go through the
    dense re-map; null keys must stay -1 (never match) afterwards."""
    n = 60_000
    left = pa.table(
        {
            "a": pa.array(
                [None] + list(range(1, n)), type=pa.int64()
            ),  # one null build key
            "b": pa.array(np.arange(n) * 7 % (n + 13), type=pa.int64()),
            "lv": pa.array(np.arange(n, dtype=np.int64)),
        }
    )
    right = pa.table(
        {
            "x": pa.array([None, 5, 10, None, 999999], type=pa.int64()),
            "y": pa.array(
                [int(left.column("b")[1].as_py()), 35 % (n + 13),
                 70 % (n + 13), 3, 4],
                type=pa.int64(),
            ),
            "rv": pa.array([1.0, 2.0, 3.0, 4.0, 5.0]),
        }
    )
    spmd, cfg = _plan_join(left, right, ["a", "b"], ["x", "y"], "left",
                           nl=2, nr=2)
    assert spmd is not None
    tctx = TaskContext(config=cfg, work_dir="/tmp", job_id="t")
    out = pa.Table.from_batches(list(spmd.execute(0, tctx)))
    assert spmd.last_path == "mesh"
    oracle = _host_oracle(left, right, ["a", "b"], ["x", "y"], "left")
    assert out.num_rows == oracle.num_rows
    # the null-key left row appears exactly once, unmatched
    null_rows = [i for i, v in enumerate(out.column("a").to_pylist())
                 if v is None]
    assert len(null_rows) == 1
    assert out.column("rv")[null_rows[0]].as_py() is None


def test_admission_declines_mesh_join_when_model_prefers_host(tmp_path):
    """Join admission rides the cost model (ISSUE 16 satellite): with the
    mesh exchange rate warm-and-slow and the inline host join warm-and-
    fast, execute() joins inline over the already-collected sides before
    ever compiling the mesh program — last_path == "host-inline", a
    recorded host_declined decision, oracle-identical rows."""
    from ballista_tpu.ops import costmodel
    from ballista_tpu.ops.runtime import join_path_stats

    dim, fact = _dim(), _fact()
    settings = {
        **SPMD_SETTINGS,
        "ballista.tpu.cost_model": "true",
        "ballista.tpu.cost_model_dir": str(tmp_path / "costs"),
    }
    spmd, cfg = _plan_join(dim, fact, ["dk"], ["fk"], "inner",
                           settings=settings)
    assert spmd is not None, "planner did not fuse the join"
    costmodel.reset(clear_dir=True)
    costmodel.configure(cfg)
    try:
        # predict falls back to the op-global rate for the unseen
        # mesh_units bucket, so one slow seed covers every join shape
        costmodel.seed("join.mesh", 1000.0, 1e6)
        costmodel.seed("join.host", 1000.0, 1e-6, engine="host")
        join_path_stats(reset=True)
        tctx = TaskContext(config=cfg, work_dir="/tmp", job_id="t")
        out = pa.Table.from_batches(list(spmd.execute(0, tctx)))
        assert spmd.last_path == "host-inline"
        stats = join_path_stats(reset=True)
        assert stats["paths"].get("host_declined") == 1
        assert any("cost model" in r for r in stats["reasons"])

        oracle = _host_oracle(dim, fact, ["dk"], ["fk"], "inner")
        assert out.num_rows == oracle.num_rows
        assert _canon(out, ["dk", "amount"]) == _canon(oracle, ["dk", "amount"])
    finally:
        costmodel.reset(clear_dir=True)

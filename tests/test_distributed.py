"""End-to-end distributed execution: standalone cluster (in-proc scheduler +
N executors + Flight data plane), mirroring the reference's docker-compose
integration tests (dev/integration-tests.sh) without containers."""

import logging
import os
import pathlib

import pyarrow as pa
import pytest

import numpy as np

from ballista_tpu.client import BallistaContext
from ballista_tpu.config import BallistaConfig
from ballista_tpu.engine import ExecutionContext
from ballista_tpu.executor.runtime import StandaloneCluster
from ballista_tpu.logical import col, functions as F, lit

logging.getLogger("ballista.executor").setLevel(logging.CRITICAL)


@pytest.fixture(scope="module")
def cluster():
    c = StandaloneCluster(n_executors=2)
    yield c
    c.shutdown()


@pytest.fixture()
def ctx(cluster, sales_table):
    host, port = cluster.scheduler_addr
    c = BallistaContext(host, port)
    c.register_record_batches("sales", sales_table, n_partitions=3)
    yield c
    c.close()


def test_distributed_aggregate(ctx):
    out = (
        ctx.table("sales")
        .aggregate([col("region")], [F.sum(col("amount")).alias("total"),
                                     F.count(col("id")).alias("n")])
        .sort(col("region").sort())
        .collect()
    )
    assert out.column("region").to_pylist() == ["east", "north", "west"]
    assert out.column("total").to_pylist() == [120.0, 40.0, 145.0]
    assert out.column("n").to_pylist() == [4, 2, 4]


def test_distributed_sql_with_limit(ctx):
    out = ctx.sql(
        "select region, sum(amount) as s from sales group by region "
        "order by s desc limit 2"
    ).collect()
    assert out.column("region").to_pylist() == ["west", "east"]


def test_shuffle_compression_roundtrip(tmp_path):
    """Shuffle pieces written with ballista.shuffle.codec=zstd read back
    transparently (the IPC frame carries the codec), shrink on disk, and the
    CLIENT-side setting actually reaches executor task execution."""
    import glob

    import numpy as np

    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.distributed.stages import read_ipc_file, write_stream_to_disk
    from ballista_tpu.executor.runtime import StandaloneCluster

    big = pa.table(
        {
            "k": pa.array(np.arange(20_000) % 64),
            "txt": pa.array([f"compressible-payload-{i % 513}" for i in range(20_000)]),
        }
    )
    path = str(tmp_path / "piece.arrow")
    stats = write_stream_to_disk(iter(big.to_batches()), big.schema, path, codec="zstd")
    assert stats.num_rows == big.num_rows
    back = pa.Table.from_batches(list(read_ipc_file(path)))
    assert back.equals(pa.Table.from_batches(big.to_batches()))

    # end-to-end: the codec travels client -> scheduler -> TaskDefinition ->
    # executor; prove it engaged by comparing materialized piece bytes
    sizes = {}
    for codec in ("", "zstd"):
        cluster = StandaloneCluster(n_executors=1, config=BallistaConfig())
        try:
            host, port = cluster.scheduler_addr
            c = BallistaContext(host, port,
                                settings={"ballista.shuffle.codec": codec})
            c.register_record_batches("big", big, n_partitions=2)
            out = (
                c.sql("select k, count(*) as n, txt from big group by k, txt "
                      "order by k, txt")
                .collect()
            )
            # (i%64, i%513) pairs are all distinct below lcm(64,513)=32832
            assert out.num_rows == 20_000
            wd = cluster.executors[0].work_dir
            sizes[codec or "none"] = sum(
                os.path.getsize(f)
                for f in glob.glob(wd + "/**/*.arrow", recursive=True)
            )
            c.close()
        finally:
            cluster.shutdown()
    assert sizes["zstd"] < sizes["none"] * 0.9, sizes


def test_distributed_filter_projection(ctx):
    out = ctx.sql(
        "select id, amount * 2 as a2 from sales where amount > 40 order by id"
    ).collect()
    assert out.column("a2").to_pylist() == [90.0, 110.0, 130.0]


def test_distributed_join(ctx, cluster):
    regions = pa.table(
        {"name": ["east", "west", "north"], "bonus": [1.0, 2.0, 3.0]}
    )
    ctx.register_record_batches("regions", regions)
    out = ctx.sql(
        "select region, sum(amount * bonus) as weighted from sales, regions "
        "where region = name group by region order by region"
    ).collect()
    assert out.column("region").to_pylist() == ["east", "north", "west"]
    assert out.column("weighted").to_pylist() == [120.0, 120.0, 290.0]


def test_distributed_failure_surfaces(ctx):
    from ballista_tpu.errors import ExecutionError

    # division by zero inside a task -> FailedTask -> job failed -> client error
    with pytest.raises(ExecutionError, match="failed"):
        ctx.sql("select id / 0 as d from sales").collect()


def test_executors_registered(ctx):
    assert len(ctx.executors()) == 2


def test_distributed_matches_local(ctx, sales_table):
    from ballista_tpu.engine import ExecutionContext

    local = ExecutionContext()
    local.register_record_batches("sales", sales_table)
    q = (
        "select region, count(*) as n, avg(amount) as m from sales "
        "where qty > 2 group by region order by region"
    )
    d = ctx.sql(q).collect().to_pylist()
    l = local.sql(q).collect().to_pylist()
    assert d == l


def test_poll_loop_enforces_data_roots(tmp_path):
    """The pull-based task path applies the EXECUTOR's scan-path allowlist
    even when the scheduler is unrestricted: the task fails on the executor
    instead of reading the file."""
    import pyarrow.parquet as pq

    from ballista_tpu.client import BallistaContext
    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.errors import ExecutionError
    from ballista_tpu.executor.runtime import BallistaExecutor, _free_port
    from ballista_tpu.scheduler.kv import MemoryBackend
    from ballista_tpu.scheduler.server import SchedulerServer, serve

    allowed = tmp_path / "data"
    allowed.mkdir()
    pq.write_table(pa.table({"x": [1.0, 2.0, 3.0]}), str(allowed / "t.parquet"))
    outside = tmp_path / "secret.parquet"
    pq.write_table(pa.table({"x": [9.0]}), str(outside))

    # scheduler: no allowlist; executor: confined to `allowed`
    impl = SchedulerServer(MemoryBackend())
    port = _free_port()
    server = serve(impl, "127.0.0.1", port)
    ex = BallistaExecutor(
        "127.0.0.1", port,
        config=BallistaConfig({"ballista.executor.data_roots": str(allowed)}),
    )
    ex.start()
    try:
        c = BallistaContext("127.0.0.1", port)
        c.register_parquet("ok", str(allowed / "t.parquet"))
        c.register_parquet("bad", str(outside))
        out = c.sql("select sum(x) as s from ok").collect()
        assert out.column("s").to_pylist() == [6.0]
        with pytest.raises(ExecutionError, match="failed"):
            c.sql("select sum(x) as s from bad").collect()
        c.close()
    finally:
        ex.stop()
        server.stop(grace=None)


def test_scheduler_enforces_data_roots(tmp_path):
    """ExecuteQuery deserializes client plans on the scheduler host; the
    scheduler's own data-root allowlist refuses out-of-root scans before
    any table source touches disk, and CREATE EXTERNAL TABLE likewise."""
    import pyarrow.parquet as pq

    from ballista_tpu.client import BallistaContext
    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.errors import BallistaError
    from ballista_tpu.executor.runtime import StandaloneCluster

    allowed = tmp_path / "data"
    allowed.mkdir()
    pq.write_table(pa.table({"x": [1.0, 2.0]}), str(allowed / "t.parquet"))
    outside = tmp_path / "secret.parquet"
    pq.write_table(pa.table({"x": [9.0]}), str(outside))

    cluster = StandaloneCluster(
        n_executors=1,
        config=BallistaConfig({"ballista.executor.data_roots": str(allowed)}),
    )
    try:
        host, port = cluster.scheduler_addr
        c = BallistaContext(host, port)
        c.register_parquet("ok", str(allowed / "t.parquet"))
        assert c.sql("select sum(x) as s from ok").collect().column("s").to_pylist() == [3.0]
        c.register_parquet("bad", str(outside))
        with pytest.raises(BallistaError, match="data roots|failed"):
            c.sql("select sum(x) as s from bad").collect()
        # raw-SQL RPC path: CREATE EXTERNAL TABLE outside the roots refused
        # on the scheduler host before any footer read
        from ballista_tpu.proto import ballista_pb2 as pb
        from ballista_tpu.scheduler.rpc import SchedulerGrpcClient

        rpc = SchedulerGrpcClient(host, port)
        with pytest.raises(BallistaError, match="data roots"):
            rpc.execute_query(
                pb.ExecuteQueryParams(
                    sql="create external table evil stored as parquet "
                    f"location '{outside}'"
                )
            )
        rpc.close()
        c.close()
    finally:
        cluster.shutdown()


def test_get_file_metadata_direct_and_bounded(tmp_path):
    """GetFileMetadata reads footers of allowlisted paths and is capped by a
    worker-slot semaphore so metadata bursts cannot starve PollWork
    (ref lib.rs:184-222 runs it on the shared RPC runtime)."""
    import pyarrow.parquet as pq

    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.proto import ballista_pb2 as pb
    from ballista_tpu.scheduler.server import SchedulerServer
    from ballista_tpu.serde.arrow import schema_from_ipc

    root = tmp_path / "data"
    root.mkdir()
    pq.write_table(pa.table({"x": [1.0, 2.0], "y": [3, 4]}), str(root / "t.parquet"))
    srv = SchedulerServer(
        config=BallistaConfig({"ballista.executor.data_roots": str(root)})
    )
    res = srv.GetFileMetadata(
        pb.GetFileMetadataParams(path=str(root / "t.parquet"), file_type="parquet")
    )
    assert res.num_partitions == 1
    assert schema_from_ipc(res.schema_ipc).names == ["x", "y"]

    # out-of-root path refused before any footer read
    outside = tmp_path / "secret.parquet"
    pq.write_table(pa.table({"z": [9]}), str(outside))
    with pytest.raises(Exception, match="data roots"):
        srv.GetFileMetadata(
            pb.GetFileMetadataParams(path=str(outside), file_type="parquet")
        )

    # all slots held -> the RPC fails fast instead of tying up a worker
    for _ in range(4):
        assert srv._file_meta_slots.acquire(blocking=False)
    try:
        with pytest.raises(RuntimeError, match="too many concurrent"):
            srv.GetFileMetadata(
                pb.GetFileMetadataParams(
                    path=str(root / "t.parquet"), file_type="parquet"
                )
            )
    finally:
        for _ in range(4):
            srv._file_meta_slots.release()


def test_all_22_queries_through_cluster(tmp_path):
    """Every TPC-H query end-to-end through the REAL distributed path —
    scheduler gRPC, stage DAG, wire serde, 2 executors, Flight fetch —
    validated against the shared pandas oracles. The reference's
    integration suite covers 6 queries and eyeballs output
    (dev/integration-tests.sh); this asserts all 22."""
    import pathlib

    import numpy as np
    import pandas as pd
    import pyarrow.parquet as pq

    from benchmarks.tpch.datagen import generate
    from benchmarks.tpch.oracles import ORACLES

    d = tmp_path / "tpch"
    generate(str(d), sf=0.005, parts=2)
    queries = pathlib.Path(__file__).parent.parent / "benchmarks" / "tpch" / "queries"
    names = ["lineitem", "orders", "customer", "supplier", "nation", "region",
             "part", "partsupp"]
    tables = {t: pq.read_table(str(d / t)).to_pandas() for t in names}

    cluster = StandaloneCluster(n_executors=2)
    try:
        host, port = cluster.scheduler_addr
        c = BallistaContext(host, port)
        for t in names:
            c.register_parquet(t, str(d / t))
        for i in range(1, 23):
            q = f"q{i}"
            got = c.sql((queries / f"{q}.sql").read_text()).collect().to_pandas()
            want = ORACLES[q](tables)
            assert len(got) == len(want), (q, len(got), len(want))
            assert list(got.columns) == list(want.columns), q
            if not len(want):
                continue
            # full-frame comparison in a total order (ties in the query's
            # ORDER BY may legitimately permute rows between engines)
            key = list(want.columns)
            g = got.sort_values(key).reset_index(drop=True)
            w = want.sort_values(key).reset_index(drop=True)
            for cn in want.columns:
                if pd.api.types.is_float_dtype(want[cn]):
                    np.testing.assert_allclose(
                        g[cn].to_numpy().astype(float),
                        w[cn].to_numpy().astype(float),
                        rtol=1e-6, equal_nan=True, err_msg=f"{q}.{cn}",
                    )
                else:
                    assert list(g[cn]) == list(w[cn]), f"{q}.{cn}"
        c.close()
    finally:
        cluster.shutdown()


def test_distributed_tpch_with_spmd_fusion(tmp_path):
    """End-to-end through the REAL control plane with SPMD stage fusion on:
    the scheduler's DistributedPlanner emits SpmdAggregateExec/SpmdJoinExec,
    the nodes travel the wire as PhySpmd* protos, and the executor runs the
    mesh programs (8-device CPU mesh). Results must match the local host
    backend on real TPC-H queries (q12 exercises the mapped device stage,
    q3 the fact-agg pushdown under a fused co-partitioned join tree)."""
    from benchmarks.tpch.datagen import generate, register_all
    from ballista_tpu.utils import tracing

    d = tmp_path / "tpch"
    generate(str(d), sf=0.02, parts=2)
    settings = {
        "ballista.executor.backend": "tpu",
        "ballista.tpu.spmd_stages": "true",
        "ballista.tpu.mesh": "data:8",
    }
    cluster = StandaloneCluster(
        n_executors=2, config=BallistaConfig(settings)
    )
    try:
        host, port = cluster.scheduler_addr
        c = BallistaContext(host, port, settings=settings)
        register_all(c, str(d))
        local = ExecutionContext(
            BallistaConfig({"ballista.executor.backend": "cpu"})
        )
        register_all(local, str(d))
        tracing.reset()
        qdir = pathlib.Path(__file__).parent.parent / "benchmarks" / "tpch" / "queries"
        for q in ("q12", "q3"):
            sql = (qdir / f"{q}.sql").read_text()
            got = c.sql(sql).collect().to_pydict()
            want = local.sql(sql).collect().to_pydict()
            assert list(got) == list(want), q
            for k in got:
                a, b = got[k], want[k]
                if a and isinstance(a[0], float):
                    np.testing.assert_allclose(a, b, rtol=1e-3, err_msg=q)
                else:
                    assert a == b, (q, k)
        # the mesh paths must actually have run — the host fallback
        # produces identical rows, so results alone cannot catch a silent
        # regression (observed healthy: join_mesh=3, mesh=2, fallbacks=0)
        counters = tracing.counters()
        assert counters.get("spmd.join_mesh", 0) >= 1, counters
        assert counters.get("spmd.mesh", 0) >= 1, counters
        assert counters.get("spmd.host_fallback", 0) == 0, counters
        assert counters.get("spmd.join_host_fallback", 0) == 0, counters
        c.close()
    finally:
        cluster.shutdown()

"""Property tests for the order-preserving IEEE-754 <-> int bijection
(ops/floatbits.py): monotone total order over randomized samples including
-0.0, subnormals and ±inf; exact round-trip; the f64 (hi, lo) int32 plane
split's lexicographic order; and the in-program jnp variants matching the
numpy reference bit-for-bit."""

import numpy as np
import pytest

from ballista_tpu.ops import floatbits


def _samples(dtype, rng, n=4096):
    """Adversarial float sample: full-range bit patterns (excluding NaN),
    plus the documented edge cases."""
    info = np.finfo(dtype)
    itype = np.int32 if dtype == np.float32 else np.int64
    bits = rng.integers(np.iinfo(itype).min, np.iinfo(itype).max, n,
                        dtype=itype)
    vals = bits.view(dtype)
    vals = vals[~np.isnan(vals)]
    edge = np.array(
        [0.0, -0.0, np.inf, -np.inf, info.tiny, -info.tiny,
         info.smallest_subnormal, -info.smallest_subnormal,
         info.max, info.min, info.eps, 1.0, -1.0],
        dtype=dtype,
    )
    uniform = rng.uniform(-1e6, 1e6, n).astype(dtype)
    return np.concatenate([vals, edge, uniform])


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_monotone_total_order(dtype, seed):
    rng = np.random.default_rng(100 + seed)
    x = _samples(dtype, rng)
    enc = floatbits.f32_to_i32 if dtype == np.float32 else floatbits.f64_to_i64
    k = enc(x)
    # pairwise over a shuffled comparison: x < y <=> key(x) < key(y);
    # x == y (±0 collapse) <=> key equality
    y = rng.permutation(x)
    ky = enc(y)
    np.testing.assert_array_equal(x < y, k < ky)
    np.testing.assert_array_equal(x == y, k == ky)
    # argsort by key IS a float sort (stability irrelevant: keys are total)
    order = np.argsort(k, kind="stable")
    assert not np.any(np.diff(x[order]) < 0)


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_round_trip_bit_exact(dtype):
    rng = np.random.default_rng(7)
    x = _samples(dtype, rng)
    enc, dec = (
        (floatbits.f32_to_i32, floatbits.i32_to_f32)
        if dtype == np.float32
        else (floatbits.f64_to_i64, floatbits.i64_to_f64)
    )
    back = dec(enc(x))
    itype = np.int32 if dtype == np.float32 else np.int64
    xb, bb = x.view(itype), back.view(itype)
    negzero = x == 0.0
    # every value except -0.0 round-trips to the identical bit pattern
    np.testing.assert_array_equal(xb[~negzero], bb[~negzero])
    # the documented collapse: both zeros decode as +0.0
    assert np.all(bb[negzero] == 0)
    # ±0 collapse to key 0
    assert np.all(enc(np.array([0.0, -0.0], dtype=dtype)) == 0)


def test_nan_keys_sort_outside_infinities():
    """+NaN keys above +inf, -NaN keys below -inf (documented policy; the
    aggregate path declines NaN inputs before keys are built)."""
    pnan = np.array([np.nan], dtype=np.float32)
    nnan = -pnan
    inf = np.array([np.inf], dtype=np.float32)
    assert floatbits.f32_to_i32(pnan)[0] > floatbits.f32_to_i32(inf)[0]
    assert floatbits.f32_to_i32(nnan)[0] < floatbits.f32_to_i32(-inf)[0]
    p64 = np.array([np.nan], dtype=np.float64)
    i64 = np.array([np.inf], dtype=np.float64)
    assert floatbits.f64_to_i64(p64)[0] > floatbits.f64_to_i64(i64)[0]
    assert floatbits.f64_to_i64(-p64)[0] < floatbits.f64_to_i64(-i64)[0]


@pytest.mark.parametrize("seed", [0, 1])
def test_plane_split_lexicographic_order(seed):
    """(hi, lo) int32 planes: lexicographic signed order == i64 key order,
    and planes_to_i64 inverts exactly (also from int64-widened planes, the
    form device readbacks arrive in)."""
    rng = np.random.default_rng(300 + seed)
    x = _samples(np.float64, rng, n=2048)
    k = floatbits.f64_to_i64(x)
    hi, lo = floatbits.i64_to_planes(k)
    assert hi.dtype == np.int32 and lo.dtype == np.int32
    np.testing.assert_array_equal(floatbits.planes_to_i64(hi, lo), k)
    np.testing.assert_array_equal(
        floatbits.planes_to_i64(hi.astype(np.int64), lo.astype(np.int64)), k
    )
    perm = rng.permutation(len(k))
    lex_lt = (hi < hi[perm]) | ((hi == hi[perm]) & (lo < lo[perm]))
    np.testing.assert_array_equal(lex_lt, k < k[perm])


def test_jnp_variants_match_numpy():
    import jax.numpy as jnp

    rng = np.random.default_rng(11)
    x = _samples(np.float32, rng, n=1024)
    k = floatbits.f32_to_i32(x)
    kj = np.asarray(floatbits.jnp_f32_to_i32(jnp.asarray(x)))
    np.testing.assert_array_equal(k, kj)
    xj = np.asarray(floatbits.jnp_i32_to_f32(jnp.asarray(k)))
    np.testing.assert_array_equal(floatbits.i32_to_f32(k).view(np.int32),
                                  xj.view(np.int32))


def test_minmax_equals_float_extrema():
    """The whole point: integer min/max over keys inverts to the bit-exact
    float min/max (negative-heavy, subnormal and ±0 mixes included)."""
    rng = np.random.default_rng(13)
    for dtype, enc, dec in (
        (np.float32, floatbits.f32_to_i32, floatbits.i32_to_f32),
        (np.float64, floatbits.f64_to_i64, floatbits.i64_to_f64),
    ):
        x = _samples(dtype, rng)
        x = x[np.isfinite(x) | np.isinf(x)]
        k = enc(x)
        got_min = dec(np.array([k.min()], dtype=k.dtype))[0]
        got_max = dec(np.array([k.max()], dtype=k.dtype))[0]
        assert got_min == x.min() and got_max == x.max()
        # bit-identical too (modulo the -0.0 collapse)
        if x.min() != 0.0:
            itype = np.int32 if dtype == np.float32 else np.int64
            assert np.array([got_min], dtype=dtype).view(itype)[0] == \
                np.array([x.min()], dtype=dtype).view(itype)[0]

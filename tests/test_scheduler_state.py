"""Scheduler state machine tests.

Mirrors the reference's scenario matrix against the in-memory backend
(rust/scheduler/src/state/mod.rs:450-787): executor metadata + namespaces,
job metadata, task statuses, and the synchronize_job_status transitions.
Also the KV backend contract tests (ref standalone.rs:103-153) for both
Memory and Sqlite backends.
"""

import sys

import pytest

from ballista_tpu.proto import ballista_pb2 as pb
from ballista_tpu.scheduler.kv import EtcdBackend, MemoryBackend, SqliteBackend
from ballista_tpu.scheduler.state import SchedulerState

import fake_etcd3


def _etcd_backend():
    """EtcdBackend against the in-process etcd fake (no client library or
    server ships in the image; the fake reproduces the semantics —
    ref rust/scheduler/src/state/etcd.rs:41-113)."""
    fake_etcd3.reset()
    sys.modules["etcd3"] = fake_etcd3
    return EtcdBackend("127.0.0.1:2379")


@pytest.fixture(params=["memory", "sqlite", "etcd"])
def kv(request):
    if request.param == "memory":
        return MemoryBackend()
    if request.param == "etcd":
        return _etcd_backend()
    return SqliteBackend.temporary()


def test_kv_contract(kv):
    assert kv.get("missing") is None
    kv.put("a/1", b"x")
    kv.put("a/2", b"y")
    kv.put("b/1", b"z")
    assert kv.get("a/1") == b"x"
    assert kv.get_prefix("a/") == [("a/1", b"x"), ("a/2", b"y")]
    kv.put("a/1", b"x2")
    assert kv.get("a/1") == b"x2"
    kv.delete_prefix("a/")
    assert kv.get_prefix("a/") == []
    assert kv.get("b/1") == b"z"


def test_kv_lease_expiry(kv):
    # etcd leases are whole seconds (1s minimum); embedded backends take
    # fractional leases
    ttl, wait = (1, 1.15) if isinstance(kv, EtcdBackend) else (0.05, 0.1)
    kv.put("lease/1", b"v", lease_seconds=ttl)
    assert kv.get("lease/1") == b"v"
    import time

    time.sleep(wait)
    assert kv.get("lease/1") is None
    assert kv.get_prefix("lease/") == []


def test_etcd_global_lock_mutual_exclusion():
    """Two clients of the same endpoint contend on /ballista_global_lock
    (ref etcd.rs:89-113): the critical sections must serialize."""
    import threading
    import time as _t

    a = _etcd_backend()
    sys.modules["etcd3"] = fake_etcd3  # second client, same fake server
    b = EtcdBackend("127.0.0.1:2379")

    order = []

    def worker(backend, name):
        with backend.lock():
            order.append((name, "in"))
            _t.sleep(0.05)
            order.append((name, "out"))

    t1 = threading.Thread(target=worker, args=(a, "a"))
    t2 = threading.Thread(target=worker, args=(b, "b"))
    t1.start(); t2.start(); t1.join(); t2.join()
    # no interleaving: each "in" is immediately followed by its own "out"
    assert order[0][1] == "in" and order[1] == (order[0][0], "out")
    assert order[2][1] == "in" and order[3] == (order[2][0], "out")


def test_etcd_scheduler_state_roundtrip():
    """The full SchedulerState machinery works over the etcd backend, like
    the reference's etcd-backed scheduler (ref state/mod.rs over etcd.rs)."""
    kv = _etcd_backend()
    s = SchedulerState(kv, "nsX")
    s.save_executor_metadata(_meta("e9"))
    assert [m.id for m in s.get_executors_metadata()] == ["e9"]
    status = pb.JobStatus()
    status.queued.SetInParent()
    s.save_job_metadata("jobZ", status)
    got = s.get_job_metadata("jobZ")
    assert got is not None and got.WhichOneof("status") == "queued"


def _meta(i="exec1", host="h", port=50051):
    return pb.ExecutorMetadata(id=i, host=host, port=port)


def test_executor_metadata_and_namespaces(kv):
    s1 = SchedulerState(kv, "ns1")
    s2 = SchedulerState(kv, "ns2")
    s1.save_executor_metadata(_meta("e1"))
    s1.save_executor_metadata(_meta("e2"))
    assert {m.id for m in s1.get_executors_metadata()} == {"e1", "e2"}
    # namespace isolation (ref state tests)
    assert s2.get_executors_metadata() == []


def _pending(job, stage, part):
    t = pb.TaskStatus()
    t.partition_id.job_id = job
    t.partition_id.stage_id = stage
    t.partition_id.partition_id = part
    return t


def _completed(job, stage, part, executor="e1", path="/tmp/x"):
    t = _pending(job, stage, part)
    t.completed.executor_id = executor
    t.completed.path = path
    return t


def _failed(job, stage, part, error="boom"):
    t = _pending(job, stage, part)
    t.failed.error = error
    return t


def _running(job, stage, part, executor="e1"):
    t = _pending(job, stage, part)
    t.running.executor_id = executor
    return t


class TestSynchronizeJobStatus:
    """The 6 scenarios from ref state/mod.rs tests."""

    def _state(self, kv):
        s = SchedulerState(kv, "test")
        running = pb.JobStatus()
        running.running.SetInParent()
        s.save_job_metadata("job", running)
        return s

    def test_all_pending_stays_running(self, kv):
        s = self._state(kv)
        s.save_task_status(_pending("job", 1, 0))
        s.save_task_status(_pending("job", 1, 1))
        s.synchronize_job_status("job")
        assert s.get_job_metadata("job").WhichOneof("status") == "running"

    def test_some_running_stays_running(self, kv):
        s = self._state(kv)
        s.save_task_status(_running("job", 1, 0))
        s.save_task_status(_completed("job", 1, 1))
        s.synchronize_job_status("job")
        assert s.get_job_metadata("job").WhichOneof("status") == "running"

    def test_any_failed_fails_job(self, kv):
        s = self._state(kv)
        s.save_task_status(_completed("job", 1, 0))
        s.save_task_status(_failed("job", 1, 1, "disk full"))
        s.synchronize_job_status("job")
        st = s.get_job_metadata("job")
        assert st.WhichOneof("status") == "failed"
        assert "disk full" in st.failed.error

    def test_all_completed_completes_with_final_stage_locations(self, kv):
        s = self._state(kv)
        s.save_executor_metadata(_meta("e1", "host1", 1234))
        s.save_task_status(_completed("job", 1, 0, path="/a"))
        s.save_task_status(_completed("job", 2, 0, path="/b"))
        s.save_task_status(_completed("job", 2, 1, path="/c"))
        s.synchronize_job_status("job")
        st = s.get_job_metadata("job")
        assert st.WhichOneof("status") == "completed"
        locs = st.completed.partition_location
        # only the FINAL stage (2) contributes result locations
        assert [pl.path for pl in locs] == ["/b", "/c"]
        assert locs[0].executor_meta.host == "host1"

    def test_queued_job_not_touched(self, kv):
        s = SchedulerState(kv, "test")
        queued = pb.JobStatus()
        queued.queued.SetInParent()
        s.save_job_metadata("job", queued)
        s.synchronize_job_status("job")
        assert s.get_job_metadata("job").WhichOneof("status") == "queued"

    def test_no_tasks_no_change(self, kv):
        s = self._state(kv)
        s.synchronize_job_status("job")
        assert s.get_job_metadata("job").WhichOneof("status") == "running"


class TestAssignment:
    def test_no_pending_tasks(self, kv):
        s = SchedulerState(kv, "t")
        assert s.assign_next_schedulable_task("e1") is None

    def test_assignment_respects_dependencies(self, kv):
        import pyarrow as pa

        from ballista_tpu.datasource import MemoryTableSource
        from ballista_tpu.distributed.planner import DistributedPlanner
        from ballista_tpu.engine import ExecutionContext
        from ballista_tpu.logical import col, functions as F

        ctx = ExecutionContext()
        ctx.register_record_batches(
            "t", pa.table({"g": ["a", "b"], "v": [1.0, 2.0]}), n_partitions=2
        )
        df = ctx.table("t").aggregate([col("g")], [F.sum(col("v")).alias("s")])
        physical = ctx.create_physical_plan(df.logical_plan())
        stages = DistributedPlanner().plan_query_stages("job", physical)
        assert len(stages) >= 2

        s = SchedulerState(kv, "t")
        s.save_executor_metadata(_meta("e1"))
        for st in stages:
            s.save_stage_plan("job", st.stage_id, st)
            for p in range(st.output_partitioning().partition_count()):
                s.save_task_status(_pending("job", st.stage_id, p))

        # only stage-1 tasks are runnable initially
        assigned = s.assign_next_schedulable_task("e1")
        assert assigned is not None
        status, _plan = assigned
        assert status.partition_id.stage_id == stages[0].stage_id
        # downstream stage must NOT be assigned while stage 1 is incomplete
        second = s.assign_next_schedulable_task("e1")
        if second is not None:
            assert second[0].partition_id.stage_id == stages[0].stage_id


def test_real_etcd_if_available():
    """KvBackend contract against a REAL etcd daemon. The image bakes
    neither an etcd binary nor an etcd3 client (PARITY.md disposition), so
    this skips here — a CI with etcd on PATH runs the same lease/prefix/
    lock contract the fake is held to (reference dials real etcd in
    rust/benchmarks/tpch/docker-compose.yaml:1-43)."""
    import shutil

    if shutil.which("etcd") is None:
        pytest.skip("no etcd binary in image")
    # the fixture tests install tests/fake_etcd3 under sys.modules["etcd3"];
    # evict it so both this gate and EtcdBackend.__init__ resolve the REAL
    # client — otherwise this test would pass vacuously against the fake
    saved = sys.modules.pop("etcd3", None)
    if saved is not None and "fake" not in getattr(saved, "__name__", ""):
        sys.modules["etcd3"] = saved  # a real client was already imported
        saved = None
    try:
        try:
            import etcd3
        except ImportError:
            pytest.skip("no etcd3 client library in image")
        assert "fake" not in etcd3.__name__
        _run_real_etcd_contract()
    finally:
        if saved is not None:
            sys.modules["etcd3"] = saved


def _run_real_etcd_contract():
    import socket
    import subprocess
    import tempfile
    import time as _time

    with socket.socket() as s:  # a free port, not a hardcoded one
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    url = f"http://127.0.0.1:{port}"
    with tempfile.TemporaryDirectory() as d:
        proc = subprocess.Popen(
            ["etcd", "--data-dir", d,
             "--listen-client-urls", url,
             "--advertise-client-urls", url],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        try:
            # readiness poll: a loaded CI host can take >2s to serve
            deadline = _time.monotonic() + 30
            kv = None
            while True:
                if proc.poll() is not None:
                    pytest.skip(f"etcd exited rc={proc.returncode} at startup")
                try:
                    kv = EtcdBackend(f"127.0.0.1:{port}")
                    kv.get("/ballista/ready")
                    break
                except Exception:
                    if _time.monotonic() > deadline:
                        raise
                    _time.sleep(0.25)
            kv.put("/ballista/x", b"1")
            assert kv.get("/ballista/x") == b"1"
            kv.put("/ballista/y", b"2")
            assert [k for k, _ in kv.get_prefix("/ballista/")] == [
                "/ballista/x", "/ballista/y",
            ]
            with kv.lock():
                pass
        finally:
            proc.terminate()
            proc.wait(timeout=10)

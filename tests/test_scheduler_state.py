"""Scheduler state machine tests.

Mirrors the reference's scenario matrix against the in-memory backend
(rust/scheduler/src/state/mod.rs:450-787): executor metadata + namespaces,
job metadata, task statuses, and the synchronize_job_status transitions.
Also the KV backend contract tests (ref standalone.rs:103-153) for both
Memory and Sqlite backends.
"""

import sys

import pytest

from ballista_tpu.proto import ballista_pb2 as pb
from ballista_tpu.scheduler.kv import EtcdBackend, MemoryBackend, SqliteBackend
from ballista_tpu.scheduler.state import SchedulerState

import fake_etcd3


def _etcd_backend():
    """EtcdBackend against the in-process etcd fake (no client library or
    server ships in the image; the fake reproduces the semantics —
    ref rust/scheduler/src/state/etcd.rs:41-113)."""
    fake_etcd3.reset()
    sys.modules["etcd3"] = fake_etcd3
    return EtcdBackend("127.0.0.1:2379")


@pytest.fixture(params=["memory", "sqlite", "etcd"])
def kv(request):
    if request.param == "memory":
        return MemoryBackend()
    if request.param == "etcd":
        return _etcd_backend()
    return SqliteBackend.temporary()


def test_kv_contract(kv):
    assert kv.get("missing") is None
    kv.put("a/1", b"x")
    kv.put("a/2", b"y")
    kv.put("b/1", b"z")
    assert kv.get("a/1") == b"x"
    assert kv.get_prefix("a/") == [("a/1", b"x"), ("a/2", b"y")]
    kv.put("a/1", b"x2")
    assert kv.get("a/1") == b"x2"
    kv.delete_prefix("a/")
    assert kv.get_prefix("a/") == []
    assert kv.get("b/1") == b"z"


def test_kv_lease_expiry(kv):
    # etcd leases are whole seconds (1s minimum); embedded backends take
    # fractional leases
    ttl, wait = (1, 1.15) if isinstance(kv, EtcdBackend) else (0.05, 0.1)
    kv.put("lease/1", b"v", lease_seconds=ttl)
    assert kv.get("lease/1") == b"v"
    import time

    time.sleep(wait)
    assert kv.get("lease/1") is None
    assert kv.get_prefix("lease/") == []


def test_kv_lease_and_fenced_cas_conformance(kv):
    """ISSUE 20 lease + fenced-CAS contract, identical across all three
    backends (the replicated control plane must behave the same over
    memory, sqlite, and etcd)."""
    import time

    ttl, wait = (1, 1.15) if isinstance(kv, EtcdBackend) else (0.05, 0.1)

    # lease_grant = TTL write; renew extends it past the original expiry
    kv.lease_grant("leases/j1", b"owner-a", ttl)
    assert kv.get("leases/j1") == b"owner-a"
    for _ in range(2):
        time.sleep(ttl * 0.6)
        assert kv.lease_renew("leases/j1", ttl) is True
    assert kv.get("leases/j1") == b"owner-a"  # renewals kept it alive
    time.sleep(wait)
    assert kv.get("leases/j1") is None
    # renewing an expired (or never-granted) key refuses: the caller has
    # been deposed and must not write as if it still held the lease
    assert kv.lease_renew("leases/j1", ttl) is False
    assert kv.lease_renew("leases/never", ttl) is False

    # fenced CAS: matching guard lands the whole batch
    kv.put("leases/j2", b"fence-1")
    assert kv.put_all(
        [("ledger/j2/a", b"x")], compare=("leases/j2", b"fence-1")
    ) is True
    assert kv.get("ledger/j2/a") == b"x"
    # mismatched guard rejects the whole batch, writing nothing
    assert kv.put_all(
        [("ledger/j2/a", b"stale"), ("ledger/j2/b", b"stale")],
        compare=("leases/j2", b"fence-0"),
    ) is False
    assert kv.get("ledger/j2/a") == b"x"
    assert kv.get("ledger/j2/b") is None

    # expect-absent (expected=None) claims exactly once
    assert kv.put_all(
        [("claimed/j3", b"by-a")], compare=("leases/j3", None)
    ) is True
    kv.put("leases/j3", b"fence-a")
    assert kv.put_all(
        [("claimed/j3", b"by-b")], compare=("leases/j3", None)
    ) is False
    assert kv.get("claimed/j3") == b"by-a"

    # leases ride the batch atomically (minted WITH the commit) and expire
    assert kv.put_all(
        [("jobs/j4", b"queued")],
        compare=("leases/j4", None),
        leases=[("leases/j4", b"owner-a", ttl)],
    ) is True
    assert kv.get("leases/j4") == b"owner-a"
    # ... and guard later fenced writes by value
    assert kv.put_all(
        [("ledger/j4/a", b"y")], compare=("leases/j4", b"owner-a")
    ) is True
    time.sleep(wait)
    # an EXPIRED guard compares as absent: the fenced write of a live
    # owner fails, and an expect-absent re-mint succeeds (lazy re-mint)
    assert kv.put_all(
        [("ledger/j4/b", b"z")], compare=("leases/j4", b"owner-a")
    ) is False
    assert kv.get("ledger/j4/b") is None
    assert kv.put_all(
        [("ledger/j4/b", b"z")],
        compare=("leases/j4", None),
        leases=[("leases/j4", b"owner-a2", ttl)],
    ) is True
    assert kv.get("ledger/j4/b") == b"z"


def test_etcd_global_lock_mutual_exclusion():
    """Two clients of the same endpoint contend on /ballista_global_lock
    (ref etcd.rs:89-113): the critical sections must serialize."""
    import threading
    import time as _t

    a = _etcd_backend()
    sys.modules["etcd3"] = fake_etcd3  # second client, same fake server
    b = EtcdBackend("127.0.0.1:2379")

    order = []

    def worker(backend, name):
        with backend.lock():
            order.append((name, "in"))
            _t.sleep(0.05)
            order.append((name, "out"))

    t1 = threading.Thread(target=worker, args=(a, "a"))
    t2 = threading.Thread(target=worker, args=(b, "b"))
    t1.start(); t2.start(); t1.join(); t2.join()
    # no interleaving: each "in" is immediately followed by its own "out"
    assert order[0][1] == "in" and order[1] == (order[0][0], "out")
    assert order[2][1] == "in" and order[3] == (order[2][0], "out")


def test_etcd_scheduler_state_roundtrip():
    """The full SchedulerState machinery works over the etcd backend, like
    the reference's etcd-backed scheduler (ref state/mod.rs over etcd.rs)."""
    kv = _etcd_backend()
    s = SchedulerState(kv, "nsX")
    s.save_executor_metadata(_meta("e9"))
    assert [m.id for m in s.get_executors_metadata()] == ["e9"]
    status = pb.JobStatus()
    status.queued.SetInParent()
    s.save_job_metadata("jobZ", status)
    got = s.get_job_metadata("jobZ")
    assert got is not None and got.WhichOneof("status") == "queued"


def _meta(i="exec1", host="h", port=50051):
    return pb.ExecutorMetadata(id=i, host=host, port=port)


def test_executor_metadata_and_namespaces(kv):
    s1 = SchedulerState(kv, "ns1")
    s2 = SchedulerState(kv, "ns2")
    s1.save_executor_metadata(_meta("e1"))
    s1.save_executor_metadata(_meta("e2"))
    assert {m.id for m in s1.get_executors_metadata()} == {"e1", "e2"}
    # namespace isolation (ref state tests)
    assert s2.get_executors_metadata() == []


def _pending(job, stage, part):
    t = pb.TaskStatus()
    t.partition_id.job_id = job
    t.partition_id.stage_id = stage
    t.partition_id.partition_id = part
    return t


def _completed(job, stage, part, executor="e1", path="/tmp/x"):
    t = _pending(job, stage, part)
    t.completed.executor_id = executor
    t.completed.path = path
    return t


def _failed(job, stage, part, error="boom"):
    t = _pending(job, stage, part)
    t.failed.error = error
    return t


def _running(job, stage, part, executor="e1"):
    t = _pending(job, stage, part)
    t.running.executor_id = executor
    return t


class TestSynchronizeJobStatus:
    """The 6 scenarios from ref state/mod.rs tests."""

    def _state(self, kv):
        s = SchedulerState(kv, "test")
        running = pb.JobStatus()
        running.running.SetInParent()
        s.save_job_metadata("job", running)
        return s

    def test_all_pending_stays_running(self, kv):
        s = self._state(kv)
        s.save_task_status(_pending("job", 1, 0))
        s.save_task_status(_pending("job", 1, 1))
        s.synchronize_job_status("job")
        assert s.get_job_metadata("job").WhichOneof("status") == "running"

    def test_some_running_stays_running(self, kv):
        s = self._state(kv)
        s.save_task_status(_running("job", 1, 0))
        s.save_task_status(_completed("job", 1, 1))
        s.synchronize_job_status("job")
        assert s.get_job_metadata("job").WhichOneof("status") == "running"

    def test_any_failed_fails_job(self, kv):
        # with retries DISABLED the reference semantics hold: first task
        # failure fails the job (retry-enabled folds are pinned in
        # tests/test_fault_tolerance.py)
        from ballista_tpu.config import BallistaConfig

        s = self._state(kv)
        s.config = BallistaConfig({"ballista.shuffle.max_task_retries": "0"})
        s.save_task_status(_completed("job", 1, 0))
        s.save_task_status(_failed("job", 1, 1, "disk full"))
        s.synchronize_job_status("job")
        st = s.get_job_metadata("job")
        assert st.WhichOneof("status") == "failed"
        assert "disk full" in st.failed.error

    def test_failed_task_requeues_within_budget(self, kv):
        # default budget (3): the same failure REQUEUES the task with its
        # history recorded instead of failing the job
        s = self._state(kv)
        s.save_task_status(_completed("job", 1, 0))
        s.save_task_status(_failed("job", 1, 1, "disk full"))
        s.synchronize_job_status("job")
        assert s.get_job_metadata("job").WhichOneof("status") == "running"
        t = s.get_task_status("job", 1, 1)
        assert t.WhichOneof("status") is None  # pending again
        assert t.attempt == 1
        assert [h.error for h in t.history] == ["disk full"]

    def test_all_completed_completes_with_final_stage_locations(self, kv):
        s = self._state(kv)
        s.save_executor_metadata(_meta("e1", "host1", 1234))
        s.save_task_status(_completed("job", 1, 0, path="/a"))
        s.save_task_status(_completed("job", 2, 0, path="/b"))
        s.save_task_status(_completed("job", 2, 1, path="/c"))
        s.synchronize_job_status("job")
        st = s.get_job_metadata("job")
        assert st.WhichOneof("status") == "completed"
        locs = st.completed.partition_location
        # only the FINAL stage (2) contributes result locations
        assert [pl.path for pl in locs] == ["/b", "/c"]
        assert locs[0].executor_meta.host == "host1"

    def test_queued_job_not_touched(self, kv):
        s = SchedulerState(kv, "test")
        queued = pb.JobStatus()
        queued.queued.SetInParent()
        s.save_job_metadata("job", queued)
        s.synchronize_job_status("job")
        assert s.get_job_metadata("job").WhichOneof("status") == "queued"

    def test_no_tasks_no_change(self, kv):
        s = self._state(kv)
        s.synchronize_job_status("job")
        assert s.get_job_metadata("job").WhichOneof("status") == "running"


class TestAssignment:
    def test_no_pending_tasks(self, kv):
        s = SchedulerState(kv, "t")
        assert s.assign_next_schedulable_task("e1") is None

    def test_assignment_respects_dependencies(self, kv):
        import pyarrow as pa

        from ballista_tpu.datasource import MemoryTableSource
        from ballista_tpu.distributed.planner import DistributedPlanner
        from ballista_tpu.engine import ExecutionContext
        from ballista_tpu.logical import col, functions as F

        ctx = ExecutionContext()
        ctx.register_record_batches(
            "t", pa.table({"g": ["a", "b"], "v": [1.0, 2.0]}), n_partitions=2
        )
        df = ctx.table("t").aggregate([col("g")], [F.sum(col("v")).alias("s")])
        physical = ctx.create_physical_plan(df.logical_plan())
        stages = DistributedPlanner().plan_query_stages("job", physical)
        assert len(stages) >= 2

        s = SchedulerState(kv, "t")
        s.save_executor_metadata(_meta("e1"))
        for st in stages:
            s.save_stage_plan("job", st.stage_id, st)
            for p in range(st.output_partitioning().partition_count()):
                s.save_task_status(_pending("job", st.stage_id, p))

        # only stage-1 tasks are runnable initially
        assigned = s.assign_next_schedulable_task("e1")
        assert assigned is not None
        status, _plan = assigned
        assert status.partition_id.stage_id == stages[0].stage_id
        # downstream stage must NOT be assigned while stage 1 is incomplete
        second = s.assign_next_schedulable_task("e1")
        if second is not None:
            assert second[0].partition_id.stage_id == stages[0].stage_id


def test_real_etcd_if_available():
    """KvBackend contract against a REAL etcd daemon. The image bakes
    neither an etcd binary nor an etcd3 client (PARITY.md disposition), so
    this skips here — a CI with etcd on PATH runs the same lease/prefix/
    lock contract the fake is held to (reference dials real etcd in
    rust/benchmarks/tpch/docker-compose.yaml:1-43)."""
    import shutil

    if shutil.which("etcd") is None:
        pytest.skip("no etcd binary in image")
    # the fixture tests install tests/fake_etcd3 under sys.modules["etcd3"];
    # evict it so both this gate and EtcdBackend.__init__ resolve the REAL
    # client — otherwise this test would pass vacuously against the fake
    saved = sys.modules.pop("etcd3", None)
    if saved is not None and "fake" not in getattr(saved, "__name__", ""):
        sys.modules["etcd3"] = saved  # a real client was already imported
        saved = None
    try:
        try:
            import etcd3
        except ImportError:
            pytest.skip("no etcd3 client library in image")
        assert "fake" not in etcd3.__name__
        _run_real_etcd_contract()
    finally:
        if saved is not None:
            sys.modules["etcd3"] = saved


def _run_real_etcd_contract():
    import socket
    import subprocess
    import tempfile
    import time as _time

    with socket.socket() as s:  # a free port, not a hardcoded one
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    url = f"http://127.0.0.1:{port}"
    with tempfile.TemporaryDirectory() as d:
        proc = subprocess.Popen(
            ["etcd", "--data-dir", d,
             "--listen-client-urls", url,
             "--advertise-client-urls", url],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        try:
            # readiness poll: a loaded CI host can take >2s to serve
            deadline = _time.monotonic() + 30
            kv = None
            while True:
                if proc.poll() is not None:
                    pytest.skip(f"etcd exited rc={proc.returncode} at startup")
                try:
                    kv = EtcdBackend(f"127.0.0.1:{port}")
                    kv.get("/ballista/ready")
                    break
                except Exception:
                    if _time.monotonic() > deadline:
                        raise
                    _time.sleep(0.25)
            kv.put("/ballista/x", b"1")
            assert kv.get("/ballista/x") == b"1"
            kv.put("/ballista/y", b"2")
            assert [k for k, _ in kv.get_prefix("/ballista/")] == [
                "/ballista/x", "/ballista/y",
            ]
            with kv.lock():
                pass
        finally:
            proc.terminate()
            proc.wait(timeout=10)


class _FakeShuffle:
    def __init__(self, stage_id):
        self.stage_id = stage_id


class _FakePlan:
    def __init__(self, deps):
        self.deps = [_FakeShuffle(d) for d in deps]


def _linear_scan_assign(s, executor_id):
    """The pre-index reference algorithm (full task scan in KV key order),
    kept verbatim as the differential oracle for the per-stage index."""
    from ballista_tpu.scheduler import state as state_mod

    tasks = s.get_all_tasks()
    by_stage = {}
    for t in tasks:
        by_stage.setdefault(
            (t.partition_id.job_id, t.partition_id.stage_id), []
        ).append(t)
    for task in tasks:
        if task.WhichOneof("status") is not None:
            continue
        job_id = task.partition_id.job_id
        stage_id = task.partition_id.stage_id
        plan = s.get_stage_plan(job_id, stage_id)
        if plan is None:
            continue
        unresolved = state_mod.find_unresolved_shuffles(plan)
        runnable = True
        for u in unresolved:
            upstream = by_stage.get((job_id, u.stage_id), [])
            if not upstream or any(
                t.WhichOneof("status") != "completed" for t in upstream
            ):
                runnable = False
                break
        if not runnable:
            continue
        running = pb.TaskStatus()
        running.partition_id.CopyFrom(task.partition_id)
        running.running.executor_id = executor_id
        s.save_task_status(running)
        return running
    return None


@pytest.mark.parametrize("seed", range(6))
def test_indexed_assignment_matches_linear_scan(monkeypatch, seed):
    """Randomized stage DAGs: the per-stage pending index must assign the
    exact task sequence the linear scan did, through random interleavings
    of completions (which unblock downstream stages mid-sequence)."""
    import numpy as np

    from ballista_tpu.scheduler import state as state_mod

    rng = np.random.default_rng(7000 + seed)
    plans = {}
    statuses = []
    for j in range(int(rng.integers(1, 4))):
        job = f"job{rng.integers(0, 50)}"
        n_stages = int(rng.integers(1, 13))  # 2-digit ids: "10" < "2" order
        for st in range(1, n_stages + 1):
            deps = [d for d in range(1, st) if rng.random() < 0.4]
            # an occasional dep on a stage with NO tasks: never satisfied
            if rng.random() < 0.1:
                deps.append(99)
            plans[(job, st)] = _FakePlan(deps)
            for p in range(int(rng.integers(1, 12))):
                t = pb.TaskStatus()
                t.partition_id.job_id = job
                t.partition_id.stage_id = st
                t.partition_id.partition_id = p
                w = rng.random()
                if w < 0.15:
                    t.running.executor_id = "e0"
                elif w < 0.3:
                    t.completed.executor_id = "e0"
                    t.completed.path = "p"
                statuses.append(t)

    monkeypatch.setattr(state_mod, "find_unresolved_shuffles",
                        lambda plan: plan.deps)
    monkeypatch.setattr(state_mod, "remove_unresolved_shuffles",
                        lambda plan, locations: plan)
    monkeypatch.setattr(
        SchedulerState, "get_stage_plan",
        lambda self, job_id, stage_id: plans.get((job_id, stage_id)),
    )
    monkeypatch.setattr(
        SchedulerState, "get_executor_metadata", lambda self, eid: None
    )

    def build():
        s = SchedulerState(MemoryBackend(), "t")
        for t in statuses:
            s.save_task_status(t)
        return s

    indexed, linear = build(), build()
    script = rng.random(size=4096)  # shared completion coin flips
    si = iter(script)
    got_i, got_l = [], []
    for step in si:
        a = indexed.assign_next_schedulable_task("e1")
        b = _linear_scan_assign(linear, "e1")
        key = lambda r: (
            None if r is None else (
                r.partition_id.job_id, r.partition_id.stage_id,
                r.partition_id.partition_id,
            )
        )
        assert key(a[0] if a else None) == key(b), (got_i, got_l)
        if a is None:
            break
        got_i.append(key(a[0]))
        got_l.append(key(b))
        if step < 0.7:  # complete it on both sides -> may unblock deps
            done = pb.TaskStatus()
            done.partition_id.CopyFrom(a[0].partition_id)
            done.completed.executor_id = "e1"
            done.completed.path = "p"
            indexed.save_task_status(done)
            linear.save_task_status(done)
    assert got_i == got_l
    assert len(got_i) or all(
        t.WhichOneof("status") is not None or plans[
            (t.partition_id.job_id, t.partition_id.stage_id)
        ].deps
        for t in statuses
    )


def test_peer_scheduler_completion_unblocks_downstream(monkeypatch):
    """Two SchedulerState instances over ONE KV: upstream completions
    written by a peer must unblock this instance's downstream assignment
    (the index re-reads an apparently-incomplete upstream stage from the
    KV before declaring it blocked)."""
    from ballista_tpu.scheduler import state as state_mod

    plans = {("j", 1): _FakePlan([]), ("j", 2): _FakePlan([1])}
    monkeypatch.setattr(state_mod, "find_unresolved_shuffles",
                        lambda plan: plan.deps)
    monkeypatch.setattr(state_mod, "remove_unresolved_shuffles",
                        lambda plan, locations: plan)
    monkeypatch.setattr(
        SchedulerState, "get_stage_plan",
        lambda self, job_id, stage_id: plans.get((job_id, stage_id)),
    )
    monkeypatch.setattr(
        SchedulerState, "get_executor_metadata", lambda self, eid: None
    )

    kv = MemoryBackend()
    a, b = SchedulerState(kv, "t"), SchedulerState(kv, "t")
    for st in (1, 2):
        t = pb.TaskStatus()
        t.partition_id.job_id = "j"
        t.partition_id.stage_id = st
        t.partition_id.partition_id = 0
        a.save_task_status(t)

    # b seeds its index: stage 1 pending, stage 2 blocked on it
    got = b.assign_next_schedulable_task("e-b")
    assert got is not None and got[0].partition_id.stage_id == 1
    # ...but PEER a records the completion, invisible to b's index
    done = pb.TaskStatus()
    done.partition_id.job_id = "j"
    done.partition_id.stage_id = 1
    done.partition_id.partition_id = 0
    done.completed.executor_id = "e-b"
    done.completed.path = "p"
    a.save_task_status(done)
    # within the reseed interval b still screens stage 2 out on its own
    # (stale-incomplete) view; once the periodic reseed fires, the full
    # scan folds in the peer's completion and stage 2 is assigned
    b._task_index_seeded_at = -1e9  # force the next reseed
    got = b.assign_next_schedulable_task("e-b")
    assert got is not None and got[0].partition_id.stage_id == 2


def test_peer_lost_task_reset_blocks_downstream(monkeypatch):
    """Staleness in the other direction: a peer resetting a completed
    upstream task to pending (lost-executor recovery) must BLOCK the
    downstream assignment — locations are built from fresh KV statuses,
    never from the index's memory of a completed stage (a stale 'done'
    would hand out empty executor/path shuffle locations)."""
    from ballista_tpu.scheduler import state as state_mod

    plans = {("j", 1): _FakePlan([]), ("j", 2): _FakePlan([1])}
    monkeypatch.setattr(state_mod, "find_unresolved_shuffles",
                        lambda plan: plan.deps)
    monkeypatch.setattr(state_mod, "remove_unresolved_shuffles",
                        lambda plan, locations: plan)
    monkeypatch.setattr(
        SchedulerState, "get_stage_plan",
        lambda self, job_id, stage_id: plans.get((job_id, stage_id)),
    )
    monkeypatch.setattr(
        SchedulerState, "get_executor_metadata", lambda self, eid: None
    )

    kv = MemoryBackend()
    a, b = SchedulerState(kv, "t"), SchedulerState(kv, "t")

    def status(stage, which):
        t = pb.TaskStatus()
        t.partition_id.job_id = "j"
        t.partition_id.stage_id = stage
        t.partition_id.partition_id = 0
        if which == "completed":
            t.completed.executor_id = "e1"
            t.completed.path = "p"
        return t

    a.save_task_status(status(1, "completed"))
    a.save_task_status(status(2, "pending"))
    # b's index now believes stage 1 is done...
    assert b.assign_next_schedulable_task("e-b") is not None  # claims stage 2
    # roll back: stage 2 pending again, stage 1 RESET by the peer
    a.save_task_status(status(2, "pending"))
    b._task_index.observe(status(2, "pending"))
    a.save_task_status(status(1, "pending"))
    # stage 2 must NOT be dispatched on a bogus empty location; the fresh
    # upstream read also teaches b's index that stage 1 is pending again,
    # so the NEXT poll re-assigns stage 1
    assert b.assign_next_schedulable_task("e-b") is None
    got = b.assign_next_schedulable_task("e-b")
    assert got is not None and got[0].partition_id.stage_id == 1

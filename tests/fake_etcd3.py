"""In-process fake of the `etcd3` client API surface EtcdBackend uses.

The image ships no etcd server or client library, so the distributed
backend is exercised against this fake, which reproduces the semantics the
reference relies on (rust/scheduler/src/state/etcd.rs:41-113): KV get /
prefix scan (sorted, key bytes in metadata), put with TTL leases (whole
seconds, keys invisible after expiry), delete_prefix, and a named mutex
lock shared by every client of the same endpoint.

Tests install it with `sys.modules["etcd3"] = fake_etcd3` before
constructing EtcdBackend.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Optional, Tuple


class _Server:
    """State shared by every client dialing the same endpoint."""

    def __init__(self) -> None:
        self.data: Dict[str, Tuple[bytes, Optional[float]]] = {}
        self.mu = threading.RLock()
        self.locks: Dict[str, threading.Lock] = {}


_servers: Dict[str, _Server] = {}
_registry_mu = threading.Lock()


def reset() -> None:
    with _registry_mu:
        _servers.clear()


class _Lease:
    def __init__(self, ttl: int, server: Optional[_Server] = None) -> None:
        if ttl < 1:
            raise ValueError("etcd lease TTL must be >= 1 second")
        self.ttl = ttl
        # keys attached to this lease (put(..., lease=)); refresh() extends
        # their expiry like etcd's lease keepalive does
        self._server = server
        self._keys: set = set()

    def refresh(self):
        if self._server is None:
            return []
        with self._server.mu:
            now = time.time()
            for k in list(self._keys):
                item = self._server.data.get(k)
                if item is None:
                    continue
                value, expires = item
                if expires is not None and now > expires:
                    continue
                self._server.data[k] = (value, now + self.ttl)
        return [self]


class _Meta:
    def __init__(self, key: str) -> None:
        self.key = key.encode()


class _Compare:
    """etcd3 compare builder: `transactions.value(k) == b"x"` yields the
    compare object itself with the expectation recorded (the real library
    overloads __eq__ the same way)."""

    def __init__(self, kind: str, key: str) -> None:
        self.kind = kind
        self.key = key
        self.expected: object = NotImplemented

    def __eq__(self, other):  # type: ignore[override]
        self.expected = other
        return self

    __hash__ = None  # compare builders are not hashable, like the real ones


class _Client:
    def __init__(self, host: str, port: int) -> None:
        endpoint = f"{host}:{port}"
        with _registry_mu:
            self._server = _servers.setdefault(endpoint, _Server())

    # -- kv ------------------------------------------------------------
    def _live(self, key: str) -> Optional[bytes]:
        item = self._server.data.get(key)
        if item is None:
            return None
        value, expires = item
        if expires is not None and time.time() > expires:
            del self._server.data[key]
            return None
        return value

    def get(self, key: str):
        with self._server.mu:
            v = self._live(key)
            return (v, _Meta(key) if v is not None else None)

    def get_prefix(self, prefix: str, sort_order: str = "ascend"):
        with self._server.mu:
            keys = sorted(k for k in self._server.data if k.startswith(prefix))
            if sort_order == "descend":
                keys.reverse()
            out = []
            for k in keys:
                v = self._live(k)
                if v is not None:
                    out.append((v, _Meta(k)))
            return out

    def put(self, key: str, value, lease: Optional[_Lease] = None) -> None:
        if isinstance(value, str):
            value = value.encode()
        with self._server.mu:
            expires = time.time() + lease.ttl if lease is not None else None
            if lease is not None:
                lease._keys.add(key)
            self._server.data[key] = (bytes(value), expires)

    def delete(self, key: str) -> None:
        with self._server.mu:
            self._server.data.pop(key, None)

    def delete_prefix(self, prefix: str) -> None:
        with self._server.mu:
            for k in [k for k in self._server.data if k.startswith(prefix)]:
                del self._server.data[k]

    # -- transactions ----------------------------------------------------
    @property
    def transactions(self):
        """etcd3's client.transactions op-builder namespace: success puts
        (lease-bearing included) plus the two compare shapes EtcdBackend's
        fenced put_all builds — `value(key) == expected` and
        `version(key) == 0` (expect-absent)."""
        class _Txns:
            @staticmethod
            def put(key, value, lease=None):
                return ("put", key, value, lease)

            @staticmethod
            def value(key):
                return _Compare("value", key)

            @staticmethod
            def version(key):
                return _Compare("version", key)

        return _Txns()

    def transaction(self, compare, success, failure):
        if failure:
            raise NotImplementedError("fake etcd3 models empty failure branches only")
        with self._server.mu:
            for c in compare:
                if not isinstance(c, _Compare) or c.expected is NotImplemented:
                    raise NotImplementedError(
                        "fake etcd3 models value/version == compares only"
                    )
                live = self._live(c.key)
                if c.kind == "value":
                    expected = c.expected
                    if isinstance(expected, str):
                        expected = expected.encode()
                    if live != expected:
                        return (False, [])
                else:  # version: 0 = absent, >=1 = present
                    if (0 if live is None else 1) != c.expected:
                        return (False, [])
            for op, key, value, lease in success:
                assert op == "put"
                if isinstance(value, str):
                    value = value.encode()
                expires = time.time() + lease.ttl if lease is not None else None
                if lease is not None:
                    lease._keys.add(key)
                self._server.data[key] = (bytes(value), expires)
        return (True, [])

    # -- lease / lock ---------------------------------------------------
    def lease(self, ttl: int) -> _Lease:
        return _Lease(int(ttl), self._server)

    @contextlib.contextmanager
    def lock(self, name: str):
        with self._server.mu:
            lk = self._server.locks.setdefault(name, threading.Lock())
        lk.acquire()
        try:
            yield
        finally:
            lk.release()


def client(host: str = "localhost", port: int = 2379) -> _Client:
    return _Client(host, port)

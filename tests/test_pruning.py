"""Parquet row-group statistics pruning (ParquetScanExec.prune_predicate)."""

import datetime

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from ballista_tpu.config import BallistaConfig
from ballista_tpu.engine import ExecutionContext
from ballista_tpu.logical import col, lit
from ballista_tpu.physical.scan import ParquetScanExec, prune_row_groups


@pytest.fixture
def sorted_parquet(tmp_path):
    """1M rows sorted by k in 10 row groups of 100k (k in [g*100, g*100+100))."""
    n = 1_000_000
    k = np.sort(np.random.default_rng(0).integers(0, 1000, n))
    t = pa.table({"k": pa.array(k, type=pa.int64()),
                  "v": pa.array(np.random.default_rng(1).uniform(0, 1, n))})
    p = tmp_path / "sorted.parquet"
    pq.write_table(t, str(p), row_group_size=100_000)
    return str(p), t


def test_prune_row_groups_skips_disjoint(sorted_parquet):
    from ballista_tpu.physical import expr as px

    path, t = sorted_parquet
    pf = pq.ParquetFile(path)
    assert pf.metadata.num_row_groups == 10
    schema = t.schema

    pred = px.BinaryPhysicalExpr(
        px.ColumnExpr("k", 0), "lt", px.LiteralExpr(150, pa.int64())
    )
    keep = prune_row_groups(pf, pred)
    assert keep and len(keep) < 10  # only the low-k groups survive

    pred2 = px.BetweenExpr(
        px.ColumnExpr("k", 0),
        px.LiteralExpr(400, pa.int64()),
        px.LiteralExpr(450, pa.int64()),
        False,
    )
    keep2 = prune_row_groups(pf, pred2)
    assert keep2 and len(keep2) <= 2

    # no predicate / unprunable predicate -> all groups
    assert prune_row_groups(pf, None) == list(range(10))
    pred3 = px.BinaryPhysicalExpr(
        px.ColumnExpr("v", 1), "plus", px.LiteralExpr(1.0, pa.float64())
    )
    assert prune_row_groups(pf, pred3) == list(range(10))


def test_pruned_query_matches_unpruned(sorted_parquet, tmp_path):
    """End-to-end: the planner attaches the hint on the streaming path and
    results are identical with pruning on and off."""
    path, t = sorted_parquet
    outs = {}
    for cache in ("true", "false"):  # false -> streaming path (pruned)
        ctx = ExecutionContext(BallistaConfig({"ballista.scan.cache": cache}))
        ctx.register_parquet("t", path)
        outs[cache] = ctx.sql(
            "select count(*) as n, sum(v) as s from t where k >= 400 and k < 450"
        ).collect()
    assert outs["true"].column("n").to_pylist() == outs["false"].column("n").to_pylist()
    np.testing.assert_allclose(
        outs["true"].column("s").to_numpy(), outs["false"].column("s").to_numpy(),
        rtol=1e-9,
    )

    # the physical plan actually carries the hint
    ctx = ExecutionContext(BallistaConfig())
    ctx.register_parquet("t", path)
    df = ctx.sql("select v from t where k < 100")
    phys = ctx.create_physical_plan(df.logical_plan())

    def find(n):
        if isinstance(n, ParquetScanExec):
            return n
        for c in n.children():
            r = find(c)
            if r is not None:
                return r
        return None

    scan = find(phys)
    assert scan is not None and scan.prune_predicate is not None


def test_prune_date_column(tmp_path):
    """Date32 statistics compare correctly against python date literals."""
    days = [datetime.date(2024, 1, 1) + datetime.timedelta(days=i) for i in range(100)]
    t = pa.table({"d": pa.array(days), "x": pa.array(range(100))})
    p = tmp_path / "dates.parquet"
    pq.write_table(t, str(p), row_group_size=25)

    from ballista_tpu.physical import expr as px

    pf = pq.ParquetFile(str(p))
    pred = px.BinaryPhysicalExpr(
        px.ColumnExpr("d", 0), "lt",
        px.LiteralExpr(datetime.date(2024, 1, 20), pa.date32()),
    )
    keep = prune_row_groups(pf, pred)
    assert keep == [0]


def test_prune_nested_schema_columns(tmp_path):
    """Metadata columns are flattened leaves: a nested column before the
    predicate column must not shift which statistics are consulted
    (review regression: wrong stats could silently drop matching rows)."""
    t = pa.table({
        "s": pa.array([{"a": 1, "b": 2}] * 100),
        "x": pa.array(range(100, 200), type=pa.int64()),
    })
    p = tmp_path / "nested.parquet"
    pq.write_table(t, str(p), row_group_size=50)

    from ballista_tpu.physical import expr as px

    pf = pq.ParquetFile(str(p))
    pred = px.BinaryPhysicalExpr(
        px.ColumnExpr("x", 1), "gt", px.LiteralExpr(50, pa.int64())
    )
    # x in [100, 200) > 50 everywhere: nothing may be pruned
    assert prune_row_groups(pf, pred) == [0, 1]
    pred2 = px.BinaryPhysicalExpr(
        px.ColumnExpr("x", 1), "lt", px.LiteralExpr(150, pa.int64())
    )
    assert prune_row_groups(pf, pred2) == [0]


def test_prune_predicate_survives_serde(sorted_parquet):
    """The hint ships to executors (scheduler -> TaskDefinition plan)."""
    from ballista_tpu.serde.physical import phys_plan_from_proto, phys_plan_to_proto

    path, _ = sorted_parquet
    ctx = ExecutionContext(BallistaConfig())
    ctx.register_parquet("t", path)
    df = ctx.sql("select v from t where k < 100")
    phys = ctx.create_physical_plan(df.logical_plan())
    back = phys_plan_from_proto(phys_plan_to_proto(phys))

    def find(n):
        if isinstance(n, ParquetScanExec):
            return n
        for c in n.children():
            r = find(c)
            if r is not None:
                return r
        return None

    scan = find(back)
    assert scan is not None and scan.prune_predicate is not None
    pf = pq.ParquetFile(path)
    assert len(prune_row_groups(pf, scan.prune_predicate)) < pf.metadata.num_row_groups

"""Mapped fact scan (ops/mappedscan.py): aggregate-over-join shapes factagg
excludes — multi-key fact joins (q7-q9) and dim-valued aggregate inputs /
fact-column group keys (q12) — rewritten to Aggregate(MappedScanExec) and
fused on the device. Reference executes these as join-materialize +
hash-aggregate (rust/core/src/serde/physical_plan/from_proto.rs:176-214)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from ballista_tpu.config import BallistaConfig
from ballista_tpu.engine import ExecutionContext
from ballista_tpu.ops import kernels
from ballista_tpu.ops.mappedscan import MappedScanExec
from ballista_tpu.ops.stage import FusedAggregateStage


@pytest.fixture(autouse=True)
def _fresh():
    kernels._stage_cache.clear()
    kernels._stage_cache_pins.clear()
    kernels._stage_latest.clear()
    yield


def _mapped_stages():
    return [
        s for s in kernels._stage_cache.values()
        if isinstance(s, FusedAggregateStage)
        and isinstance(s.scan, MappedScanExec)
    ]


def _write(tmp_path, name, table):
    p = tmp_path / f"{name}.parquet"
    pq.write_table(table, str(p))
    return str(p)


def _star(tmp_path, n_fact=30_000, n_dim=800, missing=50, seed=7):
    """Fact + dim where `missing` fact keys have NO dim row (inner join
    must drop those rows) + a second-level dim keyed on a DIM column."""
    rng = np.random.default_rng(seed)
    fact = pa.table(
        {
            "fk": pa.array(rng.integers(0, n_dim + missing, n_fact),
                           type=pa.int64()),
            "mode": pa.array([f"m{i % 5}" for i in range(n_fact)]),
            "amount": pa.array(rng.uniform(0, 100, n_fact)),
        }
    )
    dim = pa.table(
        {
            "dk": pa.array(np.arange(n_dim), type=pa.int64()),
            "prio": pa.array([f"p{i % 3}" for i in range(n_dim)]),
            "regionkey": pa.array(np.arange(n_dim, dtype=np.int64) % 7),
        }
    )
    region = pa.table(
        {
            "rk": pa.array(np.arange(7), type=pa.int64()),
            "rname": pa.array([f"region-{i}" for i in range(7)]),
        }
    )
    return (
        _write(tmp_path, "fact", fact),
        _write(tmp_path, "dim", dim),
        _write(tmp_path, "region", region),
        fact,
    )


def _ctx(backend, paths):
    ctx = ExecutionContext(
        BallistaConfig({"ballista.executor.backend": backend})
    )
    for name, p in paths.items():
        ctx.register_parquet(name, p)
    return ctx


Q_DIM_VALUED = """
    select mode,
           sum(case when prio = 'p0' then 1 else 0 end) as c0,
           sum(amount) as s
    from dim, fact
    where dk = fk
    group by mode
    order by mode
"""

# table order puts the fact join innermost, so region attaches through the
# dim-mapped `regionkey` column (a CHAINED attachment); the dim-valued
# aggregate input keeps factagg (which would otherwise claim this q10-like
# shape) out of the way
Q_CHAINED = """
    select rname, count(*) as c, sum(amount * (1 + regionkey)) as s
    from dim, fact, region
    where dk = fk and rk = regionkey
    group by rname
    order by rname
"""


def _run_both(paths, sql):
    out = {}
    for backend in ("tpu", "cpu"):
        out[backend] = _ctx(backend, paths).sql(sql).collect()
    return out["tpu"], out["cpu"]


def test_dim_valued_aggregate_inputs(tmp_path):
    """q12 shape: fact-column group key + aggregate over a dim string."""
    fp, dp, rp, _ = _star(tmp_path)
    t, c = _run_both({"fact": fp, "dim": dp}, Q_DIM_VALUED)
    assert _mapped_stages(), "mapped rewrite did not engage"
    assert t.column("mode").to_pylist() == c.column("mode").to_pylist()
    assert t.column("c0").to_pylist() == c.column("c0").to_pylist()
    np.testing.assert_allclose(
        t.column("s").to_numpy(), c.column("s").to_numpy(), rtol=1e-4
    )


def test_chained_attachment_and_membership(tmp_path):
    """q7 shape: a second dim keyed on a column the FIRST dim attached;
    fact rows with no dim match must drop (inner-join membership)."""
    fp, dp, rp, fact = _star(tmp_path)
    t, c = _run_both({"fact": fp, "dim": dp, "region": rp}, Q_CHAINED)
    assert _mapped_stages(), "mapped rewrite did not engage"
    assert t.column("rname").to_pylist() == c.column("rname").to_pylist()
    assert t.column("c").to_pylist() == c.column("c").to_pylist()
    # membership really dropped the missing-key rows
    assert sum(t.column("c").to_pylist()) < fact.num_rows
    np.testing.assert_allclose(
        t.column("s").to_numpy(), c.column("s").to_numpy(), rtol=1e-4
    )


def test_composite_key_attachment(tmp_path):
    """q9 shape: dim unique on a two-column key; out-of-range second
    components must not alias into other tuples."""
    rng = np.random.default_rng(3)
    n = 20_000
    fact = pa.table(
        {
            "k1": pa.array(rng.integers(0, 40, n), type=pa.int64()),
            # includes values beyond the dim's k2 range (0..19)
            "k2": pa.array(rng.integers(0, 30, n), type=pa.int64()),
            "v": pa.array(rng.uniform(0, 10, n)),
        }
    )
    dim_rows = [(a, b) for a in range(40) for b in range(20)]
    dim = pa.table(
        {
            "d1": pa.array([a for a, _ in dim_rows], type=pa.int64()),
            "d2": pa.array([b for _, b in dim_rows], type=pa.int64()),
            "cost": pa.array(
                [float(a * 100 + b) for a, b in dim_rows]
            ),
        }
    )
    paths = {
        "fact": _write(tmp_path, "fact", fact),
        "dim": _write(tmp_path, "dim", dim),
    }
    sql = (
        "select k1, sum(v * cost) as sc from dim, fact "
        "where d1 = k1 and d2 = k2 group by k1 order by k1"
    )
    t, c = _run_both(paths, sql)
    assert _mapped_stages(), "mapped rewrite did not engage"
    assert t.column("k1").to_pylist() == c.column("k1").to_pylist()
    np.testing.assert_allclose(
        t.column("sc").to_numpy(), c.column("sc").to_numpy(), rtol=1e-4
    )


def test_duplicate_dim_keys_fall_back_correctly(tmp_path):
    """A non-unique dim key multiplies rows; the mapped stage must decline
    at prepare and the host path must produce the multiplied result."""
    fact = pa.table(
        {
            "fk": pa.array([1, 1, 2], type=pa.int64()),
            "mode": pa.array(["a", "a", "b"]),
            "amount": pa.array([1.0, 2.0, 4.0]),
        }
    )
    dim = pa.table(
        {
            "dk": pa.array([1, 1, 2], type=pa.int64()),  # dup key 1
            "prio": pa.array(["p0", "p1", "p0"]),
        }
    )
    paths = {
        "fact": _write(tmp_path, "fact", fact),
        "dim": _write(tmp_path, "dim", dim),
    }
    sql = (
        "select mode, count(*) as c, sum(amount) as s from dim, fact "
        "where dk = fk group by mode order by mode"
    )
    t, c = _run_both(paths, sql)
    assert t.column("c").to_pylist() == c.column("c").to_pylist() == [4, 1]
    assert t.column("s").to_pylist() == c.column("s").to_pylist()


def test_null_fact_keys_drop(tmp_path):
    fact = pa.table(
        {
            "fk": pa.array([1, None, 2, None], type=pa.int64()),
            "mode": pa.array(["a", "a", "b", "b"]),
            "amount": pa.array([1.0, 2.0, 4.0, 8.0]),
        }
    )
    dim = pa.table(
        {
            "dk": pa.array([1, 2], type=pa.int64()),
            "prio": pa.array(["p0", "p1"]),
        }
    )
    paths = {
        "fact": _write(tmp_path, "fact", fact),
        "dim": _write(tmp_path, "dim", dim),
    }
    sql = (
        "select mode, sum(amount) as s from dim, fact "
        "where dk = fk group by mode order by mode"
    )
    t, c = _run_both(paths, sql)
    assert t.column("s").to_pylist() == c.column("s").to_pylist() == [1.0, 4.0]


def test_tpch_q7_q12_device_path(tmp_path):
    """The real TPC-H q7/q12 (and q8/q9 composite shapes) engage the mapped
    device path and match the host backend."""
    from benchmarks.tpch.datagen import generate, register_all

    d = tmp_path / "tpch"
    generate(str(d), sf=0.02, parts=1)
    results = {}
    for backend in ("tpu", "cpu"):
        ctx = ExecutionContext(
            BallistaConfig({"ballista.executor.backend": backend})
        )
        register_all(ctx, str(d))
        results[backend] = {}
        for q in ("q7", "q9", "q12"):
            sql = open(f"benchmarks/tpch/queries/{q}.sql").read()
            results[backend][q] = ctx.sql(sql).collect()
    assert len(_mapped_stages()) >= 3, "mapped rewrite did not engage"
    for q in ("q7", "q9", "q12"):
        t, c = results["tpu"][q], results["cpu"][q]
        assert t.num_rows == c.num_rows, q
        for name in t.schema.names:
            tv, cv = t.column(name).to_pylist(), c.column(name).to_pylist()
            if t.schema.field(name).type in (pa.float64(), pa.float32()):
                np.testing.assert_allclose(tv, cv, rtol=1e-3, err_msg=q)
            else:
                assert tv == cv, (q, name)


def test_multifile_fact_as_build_side(tmp_path):
    """The framework drives the join's PROBE-side partition count; with a
    multi-file fact on the BUILD side and a single-file dim probe, the
    rewritten stage must stripe every fact partition over the driven ones
    (missing the stride silently dropped all but one fact file)."""
    rng = np.random.default_rng(9)
    fdir = tmp_path / "factdir"
    fdir.mkdir()
    parts = []
    for p in range(3):
        n = 5000 + p * 100
        t = pa.table(
            {
                "fk": pa.array(rng.integers(0, 200, n), type=pa.int64()),
                "mode": pa.array([f"m{i % 4}" for i in range(n)]),
                "amount": pa.array(rng.uniform(0, 10, n)),
            }
        )
        pq.write_table(t, str(fdir / f"part-{p}.parquet"))
        parts.append(t)
    dim = pa.table(
        {
            "dk": pa.array(np.arange(200), type=pa.int64()),
            "prio": pa.array([f"p{i % 3}" for i in range(200)]),
        }
    )
    paths = {"fact": str(fdir), "dim": _write(tmp_path, "dim", dim)}
    # "from fact, dim" puts the multi-partition fact on the BUILD side
    sql = (
        "select mode, sum(case when prio = 'p1' then amount else 0 end) as s,"
        " count(*) as c from fact, dim where fk = dk "
        "group by mode order by mode"
    )
    t, c = _run_both(paths, sql)
    assert _mapped_stages(), "mapped rewrite did not engage"
    # counts cover ALL three fact files, not just partition 0
    assert sum(c.column("c").to_pylist()) == sum(p.num_rows for p in parts)
    assert t.column("c").to_pylist() == c.column("c").to_pylist()
    np.testing.assert_allclose(
        t.column("s").to_numpy(), c.column("s").to_numpy(), rtol=1e-4
    )


def test_float_min_equality_consumer_stays_exact(tmp_path):
    """TPC-H q2 shape: a decorrelated MIN(float) subquery whose result is
    equality-joined back against the source column. The device computes
    f32; the rounded min would match nothing — the rewrite must decline
    float MIN/MAX so the exact host value flows into the join."""
    rng = np.random.default_rng(21)
    n, nk = 8000, 400
    fact = pa.table(
        {
            "fk": pa.array(rng.integers(0, nk, n), type=pa.int64()),
            # 2-decimal "decimal" values: not exactly representable in f32
            "cost": pa.array(np.round(rng.uniform(1, 1000, n), 2)),
        }
    )
    dim = pa.table(
        {
            "dk": pa.array(np.arange(nk), type=pa.int64()),
            "attr": pa.array([f"a{i % 9}" for i in range(nk)]),
        }
    )
    paths = {
        "fact": _write(tmp_path, "fact", fact),
        "dim": _write(tmp_path, "dim", dim),
    }
    sql = (
        "select fk, cost from dim, fact where dk = fk and cost = ("
        "  select min(cost) from dim d2, fact f2 "
        "  where d2.dk = f2.fk and f2.fk = fact.fk"
        ") order by fk"
    )
    t, c = _run_both(paths, sql)
    assert c.num_rows >= nk  # sanity: the oracle finds every group's min
    assert t.num_rows == c.num_rows
    assert t.column("cost").to_pylist() == c.column("cost").to_pylist()


def test_semi_and_anti_membership(tmp_path):
    """q4 shape: EXISTS/NOT EXISTS become membership-only attachments —
    no columns, no uniqueness requirement, null fact keys follow SQL
    (never match; ANTI keeps them)."""
    fact = pa.table(
        {
            "fk": pa.array([1, 1, 2, 3, None, 5], type=pa.int64()),
            "mode": pa.array(["a", "b", "a", "b", "a", "b"]),
            "amount": pa.array([1.0, 2.0, 4.0, 8.0, 16.0, 32.0]),
        }
    )
    # duplicate + null keys on the membership side are fine
    sub = pa.table(
        {
            "sk": pa.array([1, 1, 3, None], type=pa.int64()),
            "x": pa.array([0.0, 1.0, 2.0, 3.0]),
        }
    )
    paths = {
        "fact": _write(tmp_path, "fact", fact),
        "sub": _write(tmp_path, "sub", sub),
    }
    for op, expected_s in (
        ("in", [1.0 + 2.0 + 8.0]),        # fk in (1, 3)
        ("not in", [4.0 + 32.0]),          # fk = 2, 5 (null fk never matches
                                           # EXISTS; NOT EXISTS keeps it —
                                           # but SQL [NOT] IN via EXISTS
                                           # decorrelation keeps nulls out)
    ):
        sql = (
            "select sum(amount) as s from fact "
            f"where fk {op} (select sk from sub where sk is not null)"
        )
        t, c = _run_both(paths, sql)
        assert c.column("s").to_pylist() == expected_s, op  # hand oracle
        assert t.column("s").to_pylist() == c.column("s").to_pylist(), op


def test_tpch_q4_device_path(tmp_path):
    from benchmarks.tpch.datagen import generate, register_all

    d = tmp_path / "tpch"
    generate(str(d), sf=0.02, parts=1)
    res = {}
    for backend in ("tpu", "cpu"):
        kernels._stage_cache.clear()
        ctx = ExecutionContext(
            BallistaConfig({"ballista.executor.backend": backend})
        )
        register_all(ctx, str(d))
        res[backend] = ctx.sql(
            open("benchmarks/tpch/queries/q4.sql").read()
        ).collect()
        if backend == "tpu":
            assert _mapped_stages(), "q4 did not engage the mapped path"
    t, c = res["tpu"], res["cpu"]
    assert t.column("o_orderpriority").to_pylist() == \
        c.column("o_orderpriority").to_pylist()
    assert t.column("order_count").to_pylist() == \
        c.column("order_count").to_pylist()


def test_composite_semi_keys_with_nulls(tmp_path):
    """Composite EXISTS keys whose dim side has nulls in DIFFERENT rows
    with equal per-column null counts: tuples must stay row-aligned (a
    per-column drop_null zipped phantom tuples)."""
    fact = pa.table(
        {
            "k1": pa.array([1, 3, 7], type=pa.int64()),
            "k2": pa.array([10, 20, 30], type=pa.int64()),
            "amount": pa.array([1.0, 2.0, 4.0]),
        }
    )
    sub = pa.table(
        {
            "s1": pa.array([1, None, 3], type=pa.int64()),
            "s2": pa.array([10, 20, None], type=pa.int64()),
        }
    )
    paths = {
        "fact": _write(tmp_path, "fact", fact),
        "sub": _write(tmp_path, "sub", sub),
    }
    # only (1, 10) is a fully-valid dim tuple -> only amount=1.0 survives
    sql = (
        "select sum(amount) as s from fact where exists ("
        "  select 1 from sub where s1 = k1 and s2 = k2)"
    )
    t, c = _run_both(paths, sql)
    assert c.column("s").to_pylist() == [1.0]
    assert t.column("s").to_pylist() == [1.0]

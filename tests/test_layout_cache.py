"""Persisted device-layout cache (ops/layout_cache.py): a fresh process
skips the O(N log N) host prepare (parquet decode, encode, rank, sort,
materialize) and goes straight to the h2d transfer.

Scan-side analog of the reference's materialize-before-consume discipline
(rust/executor/src/flight_service.rs:104-126)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from ballista_tpu.config import BallistaConfig
from ballista_tpu.engine import ExecutionContext
from ballista_tpu.ops import kernels


def _reset_stage_caches():
    """Simulate a fresh process: drop the in-memory stage cache and its HBM
    reservations so the next query rebuilds stages from scratch."""
    from ballista_tpu.ops.runtime import release_stage_residency, reset_residency

    for stage in kernels._stage_cache.values():
        if stage not in (None, False):
            release_stage_residency(stage)
    kernels._stage_cache.clear()
    kernels._stage_cache_pins.clear()
    kernels._stage_latest.clear()
    reset_residency()


@pytest.fixture(autouse=True)
def _fresh_caches():
    _reset_stage_caches()
    yield
    _reset_stage_caches()


def _ctx(cache_dir):
    return ExecutionContext(
        BallistaConfig(
            {
                "ballista.executor.backend": "tpu",
                "ballista.tpu.layout_cache_dir": str(cache_dir),
            }
        )
    )


def _make_table(n=60_000, g=3000, seed=0):
    """>1024 groups forces the sorted chunked-segment path (the persisted
    one); includes a string column so the dictionary snapshot is exercised."""
    rng = np.random.default_rng(seed)
    return pa.table(
        {
            "k": pa.array(rng.integers(0, g, n), type=pa.int64()),
            "v": pa.array(rng.uniform(-100, 100, n).astype(np.float64)),
            "s": pa.array(
                [f"tag{i}" for i in rng.integers(0, 7, n)], type=pa.string()
            ),
        }
    )


QUERY = (
    "select k, sum(v) as sv, count(v) as c, min(v) as mn, max(v) as mx "
    "from t where s <> 'tag3' group by k order by k"
)


def _run(path, cache_dir):
    ctx = _ctx(cache_dir)
    ctx.register_parquet("t", path)
    return ctx.sql(QUERY).collect()


def test_warm_start_hits_without_parquet_decode(tmp_path, monkeypatch):
    path = str(tmp_path / "t.parquet")
    pq.write_table(_make_table(), path)
    cache = tmp_path / "layouts"

    cold = _run(path, cache)
    entries = [p for p in cache.rglob("meta.json")]
    assert entries, "cold run persisted no layout entry"

    _reset_stage_caches()

    # a warm start must not touch the parquet data pages at stage-prepare
    # time (registration/planning may still read metadata)
    real_read = pq.read_table

    def _no_decode(*a, **kw):
        raise AssertionError("parquet decode on a warm start")

    monkeypatch.setattr(pq, "read_table", _no_decode)
    try:
        warm = _run(path, cache)
    finally:
        monkeypatch.setattr(pq, "read_table", real_read)
    assert warm.equals(cold)


def test_rewritten_file_misses(tmp_path):
    path = str(tmp_path / "t.parquet")
    pq.write_table(_make_table(seed=0), path)
    cache = tmp_path / "layouts"
    first = _run(path, cache)

    # rewrite with different data: the mtime-bearing stage key changes, so
    # the stale entry must miss and results must reflect the new file
    import os
    import time

    pq.write_table(_make_table(seed=1), path)
    os.utime(path, (time.time() + 5, time.time() + 5))
    _reset_stage_caches()
    second = _run(path, cache)
    assert not second.equals(first)

    # oracle for the new data
    host = ExecutionContext(
        BallistaConfig({"ballista.executor.backend": "cpu"})
    )
    host.register_parquet("t", path)
    expected = host.sql(QUERY).collect()
    sv = second.column("sv").to_numpy()
    ev = expected.column("sv").to_numpy()
    assert second.column("k").equals(expected.column("k"))
    np.testing.assert_allclose(sv, ev, rtol=1e-3)  # f32 device accumulation


def test_disabled_dir_persists_nothing(tmp_path):
    path = str(tmp_path / "t.parquet")
    pq.write_table(_make_table(), path)
    ctx = ExecutionContext(
        BallistaConfig(
            {
                "ballista.executor.backend": "tpu",
                "ballista.tpu.layout_cache_dir": "",
            }
        )
    )
    ctx.register_parquet("t", path)
    ctx.sql(QUERY).collect()
    assert not list(tmp_path.rglob("meta.json"))


def test_dictionary_prefix_refusal():
    """adopt_dict_snapshot must refuse when a live dictionary conflicts with
    the snapshot — persisted tiles bake the snapshot's codes."""
    from ballista_tpu.ops.layout_cache import (
        adopt_dict_snapshot,
        pack_dict_snapshot,
    )
    from ballista_tpu.ops.runtime import ScanDictionaries

    src = ScanDictionaries()
    d = src.for_column(0)
    d.encode(pa.array(["a", "b", "c"]))
    meta, arrays = pack_dict_snapshot(src)

    # live dict is a prefix -> adopts, codes extend
    ok = ScanDictionaries()
    ok.for_column(0).encode(pa.array(["a"]))
    assert adopt_dict_snapshot(ok, meta, arrays)
    assert len(ok.for_column(0)) == 3

    # live dict conflicts at position 0 -> refuses
    bad = ScanDictionaries()
    bad.for_column(0).encode(pa.array(["b"]))
    assert not adopt_dict_snapshot(bad, meta, arrays)

    # live dict longer than the snapshot -> refuses
    longer = ScanDictionaries()
    longer.for_column(0).encode(pa.array(["a", "b", "c", "d"]))
    assert not adopt_dict_snapshot(longer, meta, arrays)


def test_arrow_roundtrip_types():
    """Group key values of awkward Arrow types survive the IPC packing."""
    from ballista_tpu.ops.layout_cache import (
        pack_arrow_arrays,
        unpack_arrow_arrays,
    )
    import datetime

    arrays = [
        pa.array(["x", None, "z"]),
        pa.array([datetime.date(1994, 1, 1), datetime.date(1995, 2, 2), None]),
        pa.array([1.5, 2.5, 3.5]),
    ]
    out = unpack_arrow_arrays(pack_arrow_arrays(arrays))
    assert len(out) == 3
    for a, b in zip(arrays, out):
        assert a.equals(b)
    assert unpack_arrow_arrays(pack_arrow_arrays([])) == []


def test_factagg_warm_start(tmp_path, monkeypatch):
    """The fact-agg (aggregate-over-join) path flows through the same
    persisted prepare; its warm start must skip the fact-side parquet decode
    and reproduce the cold results (including the top-k epilogue)."""
    rng = np.random.default_rng(5)
    nf, nk = 20_000, 3000
    fact = pa.table(
        {
            "fk": pa.array(rng.integers(0, nk, nf), type=pa.int64()),
            "amount": pa.array(np.round(rng.uniform(1, 500, nf), 2)),
            "flag": pa.array(rng.integers(0, 2, nf), type=pa.int64()),
        }
    )
    dim = pa.table(
        {
            "dk": pa.array(np.arange(nk), type=pa.int64()),
            "attr": pa.array([f"grp-{i % 37}" for i in range(nk)]),
        }
    )
    pq.write_table(fact, str(tmp_path / "fact.parquet"))
    pq.write_table(dim, str(tmp_path / "dim.parquet"))
    cache = tmp_path / "layouts"
    q = (
        "select fk, sum(amount) as rev, attr from dim, fact "
        "where dk = fk and flag = 1 group by fk, attr "
        "order by rev desc limit 15"
    )

    def run():
        ctx = _ctx(cache)
        ctx.register_parquet("fact", str(tmp_path / "fact.parquet"))
        ctx.register_parquet("dim", str(tmp_path / "dim.parquet"))
        return ctx.sql(q).collect()

    cold = run()
    from ballista_tpu.ops.factagg import FactAggregateStage

    assert any(
        isinstance(s, FactAggregateStage)
        for s in kernels._stage_cache.values()
    ), "fact-agg stage not engaged; test would not cover its warm start"
    assert list(cache.rglob("meta.json")), "no persisted entry"
    _reset_stage_caches()

    # the fact-side decode must not run on the warm start; the (small) dim
    # side is re-read per process, so only fail on the fact file
    real_read = pq.read_table

    def _guard(path, *a, **kw):
        if "fact" in str(path):
            raise AssertionError("fact-side parquet decode on a warm start")
        return real_read(path, *a, **kw)

    monkeypatch.setattr(pq, "read_table", _guard)
    try:
        warm = run()
    finally:
        monkeypatch.setattr(pq, "read_table", real_read)
    assert warm.equals(cold)


def test_disk_hit_pins_into_device_cache(tmp_path):
    """A disk-loaded entry must be pinned like a freshly built one: inserted
    into the stage's _device_cache and reserved in the residency ledger —
    an unpinned hit would re-read the multi-GB entry from disk per query and
    hold device arrays the HBM bookkeeping never accounted for."""
    from ballista_tpu.ops.runtime import resident_bytes
    from ballista_tpu.ops.stage import FusedAggregateStage

    path = str(tmp_path / "t.parquet")
    pq.write_table(_make_table(), path)
    cache = tmp_path / "layouts"
    _run(path, cache)
    _reset_stage_caches()
    assert resident_bytes() == 0

    _run(path, cache)
    stages = [
        s for s in kernels._stage_cache.values()
        if isinstance(s, FusedAggregateStage)
    ]
    assert stages and 0 in stages[0]._device_cache
    assert stages[0]._device_cache[0]["kind"] == "sorted"
    assert resident_bytes() > 0


def test_batches_path_warm_start(tmp_path, monkeypatch):
    """Low-cardinality stages (the unrolled batches path — q1/q6 shapes)
    persist too: at SF=100 their full-scan decode is ~400 s per fresh
    process, which would eat a relay capture window."""
    rng = np.random.default_rng(4)
    n = 80_000
    table = pa.table(
        {
            "g": pa.array([f"grp{i % 5}" for i in rng.integers(0, 5, n)]),
            "v": pa.array(rng.uniform(-10, 10, n)),
            "w": pa.array(rng.integers(0, 1000, n), type=pa.int64()),
        }
    )
    path = str(tmp_path / "t.parquet")
    pq.write_table(table, path)
    cache = tmp_path / "layouts"
    q = ("select g, sum(v) as sv, count(*) as c, sum(w) as sw from t "
         "where v > -5 group by g order by g")

    def run():
        ctx = _ctx(cache)
        ctx.register_parquet("t", path)
        return ctx.sql(q).collect()

    cold = run()
    import json as _json

    metas = [_json.load(open(p)) for p in cache.rglob("meta.json")]
    # ISSUE 19: parquet-backed batches persist per-chunk delta entries
    # (one per (path, mtime, size, chunk_index)), not one whole-set blob
    assert any(m.get("kind") == "chunk" for m in metas), metas
    _reset_stage_caches()

    real_read = pq.read_table

    def _no_decode(*a, **kw):
        raise AssertionError("parquet decode on a warm start")

    monkeypatch.setattr(pq, "read_table", _no_decode)
    try:
        warm = run()
    finally:
        monkeypatch.setattr(pq, "read_table", real_read)
    assert warm.equals(cold)

"""Replicated control plane (ISSUE 20): lease-sharded job ownership,
fenced (compare-and-swap) writes, peer failover, and ownership redirects.

Unit layer: two hand-built SchedulerStates over ONE shared backend pin the
lease/fencing state machine — mint-with-commit atomicity, renewal, expiry,
adoption running restart recovery scoped to the job, and the deposed
owner's writes rejected whole with no unfenced degradation.

Server layer: in-process SchedulerServer peers pin the RPC-visible
behavior — PollWork's gate-and-partition redirect, the GetJobStatus
ownership hint, and the queued-grace sweep that fails submissions whose
planning replica died before the atomic commit.

E2E layer (the ISSUE 20 acceptance runs): a 3-replica cluster whose job
owner is killed mid-job completes bit-identical to a single-scheduler
fault-free oracle with zero task retries (failover = a peer's scoped
recovery run, not a re-execution); and a paused-then-revived deposed
owner's late writes are rejected without corrupting the adopted job.
"""

import threading
import time

import pyarrow as pa
import pytest

from ballista_tpu.config import BallistaConfig
from ballista_tpu.proto import ballista_pb2 as pb
from ballista_tpu.scheduler.kv import MemoryBackend
from ballista_tpu.scheduler.server import SchedulerServer
from ballista_tpu.scheduler.state import SchedulerState

# -- helpers -----------------------------------------------------------------


def _replica_state(kv, rid, addr, ttl="0.05"):
    cfg = BallistaConfig({"ballista.scheduler.lease_ttl_s": ttl})
    s = SchedulerState(kv, "t", cfg)
    s.replica_id = rid
    s.replica_addr = addr
    return s


def _commit_running(s, job="j"):
    """Commit a minimal 'planned' job the way planning does: the running
    flip rides the same atomic batch that mints the ownership lease."""
    running = pb.JobStatus()
    running.running.SetInParent()
    s.commit_plan_batch(
        job, [(s._key("jobs", job), running.SerializeToString())]
    )


def _meta(i):
    return pb.ExecutorMetadata(id=i, host="h", port=1)


def _pending(job, stage, part, attempt=0):
    t = pb.TaskStatus()
    t.partition_id.job_id = job
    t.partition_id.stage_id = stage
    t.partition_id.partition_id = part
    t.attempt = attempt
    return t


def _stage_plan(s, job="j", stage=1):
    from ballista_tpu.physical.basic import EmptyExec

    s.save_stage_plan(job, stage, EmptyExec(True, pa.schema([("a", pa.int64())])))


def _echo(job, stage, part, attempt):
    e = pb.RunningTaskEcho()
    e.partition_id.job_id = job
    e.partition_id.stage_id = stage
    e.partition_id.partition_id = part
    e.attempt = attempt
    return e


# -- lease + fencing state machine (unit) ------------------------------------


def test_lease_minted_atomically_with_plan_commit():
    kv = MemoryBackend()
    a = _replica_state(kv, "a", "127.0.0.1:7001", ttl="5")
    _commit_running(a)
    lease = a.job_lease("j")
    assert lease is not None
    assert lease.replica_id == "a"
    assert lease.fence == 1
    assert lease.addr == "127.0.0.1:7001"
    assert a.owns_job("j") and a.owned_jobs() == ["j"]
    # the fence counter is durable and outlives the lease
    assert kv.get("/ballista/t/leasegen/j") == b"1"
    # a peer racing the same job id loses the expect-absent CAS whole
    b = _replica_state(kv, "b", "127.0.0.1:7002", ttl="5")
    with pytest.raises(RuntimeError, match="lease race"):
        _commit_running(b)
    assert not b.owns_job("j")
    assert a.job_lease("j").replica_id == "a"


def test_renewal_keeps_ownership_against_peers():
    kv = MemoryBackend()
    a = _replica_state(kv, "a", "127.0.0.1:7001")
    b = _replica_state(kv, "b", "127.0.0.1:7002")
    _commit_running(a)
    # heartbeat at ~TTL/2 for several TTLs: the lease never lapses
    for _ in range(6):
        time.sleep(0.02)
        assert a.renew_owned_leases() == 1
    holder = b.ensure_job_writable("j")
    assert holder is not None and holder.replica_id == "a"
    assert not b.owns_job("j")


def test_peer_adopts_after_lease_expiry_with_monotonic_fence():
    from ballista_tpu.ops.runtime import recovery_stats

    kv = MemoryBackend()
    a = _replica_state(kv, "a", "127.0.0.1:7001")
    b = _replica_state(kv, "b", "127.0.0.1:7002")
    _commit_running(a)
    time.sleep(0.1)  # owner stops renewing: replica death
    recovery_stats(reset=True)
    assert b.ensure_job_writable("j") is None  # adopt-on-demand
    assert b.owns_job("j")
    lease = b.job_lease("j")
    assert lease.replica_id == "b"
    assert lease.fence == 2  # strictly past every fence the dead owner held
    stats = recovery_stats(reset=True)
    assert stats.get("lease_adopted", 0) == 1, stats


def test_deposed_owner_writes_rejected_whole_without_corruption():
    kv = MemoryBackend()
    a = _replica_state(kv, "a", "127.0.0.1:7001")
    b = _replica_state(kv, "b", "127.0.0.1:7002")
    _commit_running(a)
    time.sleep(0.1)
    assert b.ensure_job_writable("j") is None  # b adopted
    # the deposed-but-alive owner wakes up and writes as if nothing happened
    failed = pb.JobStatus()
    failed.failed.error = "stale verdict from a deposed owner"
    assert a.save_job_metadata("j", failed) is False
    assert a.fence_rejected == 1
    assert not a.owns_job("j")
    # durable truth is untouched: the adopter's running status survives
    assert b.get_job_metadata("j").WhichOneof("status") == "running"
    # deposition is remembered: even after b's lease expires, a's writes
    # never degrade to the unfenced legacy path
    time.sleep(0.1)
    assert a.save_job_metadata("j", failed) is False
    assert b.get_job_metadata("j").WhichOneof("status") == "running"


def test_expired_unclaimed_lease_self_heals():
    """Single-replica servers run no heartbeat thread: their leases expire
    mid-job routinely and the next fenced write re-mints in place."""
    from ballista_tpu.ops.runtime import recovery_stats

    kv = MemoryBackend()
    a = _replica_state(kv, "a", "127.0.0.1:7001")
    _commit_running(a)
    time.sleep(0.1)
    assert a.job_lease("j") is None  # lapsed, nobody claimed it
    recovery_stats(reset=True)
    running = pb.JobStatus()
    running.running.SetInParent()
    assert a.save_job_metadata("j", running) is True
    lease = a.job_lease("j")
    assert lease.replica_id == "a" and lease.fence == 2
    assert a.owns_job("j")
    assert recovery_stats(reset=True).get("lease_reminted", 0) == 1


def test_adoption_runs_restart_recovery_scoped_to_the_job():
    """Failover IS restart recovery run by a peer: the adopter reloads the
    dead owner's durable assignment ledger with a fresh grace window, and
    the executor's attempt-matching echo re-adopts the task — no retry."""
    from ballista_tpu.ops.runtime import recovery_stats

    kv = MemoryBackend()
    a = _replica_state(kv, "a", "127.0.0.1:7001")
    _commit_running(a)
    a.save_executor_metadata(_meta("e1"))
    _stage_plan(a)
    a.save_task_status(_pending("j", 1, 0))
    assert a.assign_next_schedulable_task("e1") is not None
    time.sleep(0.1)  # owner dies
    recovery_stats(reset=True)
    b = _replica_state(kv, "b", "127.0.0.1:7002")
    assert b.ensure_job_writable("j") is None  # adopts + scoped recover
    assert ("j", 1, 0) in b._assigned
    stats = recovery_stats()
    assert stats.get("restart_job_resumed", 0) == 1, stats
    assert stats.get("restart_assignment_restored", 0) == 1, stats
    # restart_generation untouched: no process died
    assert kv.get("/ballista/t/meta/restart_generation") is None
    # the owner executor vouches: re-adopted, not requeued
    assert b.reconcile_running_tasks("e1", [_echo("j", 1, 0, 0)]) == 0
    assert b.get_task_status("j", 1, 0).WhichOneof("status") == "running"
    assert recovery_stats(reset=True).get("task_retry", 0) == 0


# -- server-level ownership behavior -----------------------------------------


def test_pollwork_redirects_foreign_statuses_to_the_owner():
    """Gate-and-partition: a poll carrying statuses for a live peer's job
    folds nothing for it, assigns nothing, and aborts UNAVAILABLE naming
    the owner — the executor's retry loop re-homes and re-delivers."""
    from ballista_tpu.ops.runtime import recovery_stats

    kv = MemoryBackend()
    cfg = BallistaConfig({"ballista.scheduler.lease_ttl_s": "5"})
    srv_a = SchedulerServer(
        kv, config=cfg, replica_id="a", advertise_addr="127.0.0.1:7001"
    )
    srv_b = SchedulerServer(
        kv, config=cfg, replica_id="b", advertise_addr="127.0.0.1:7002"
    )
    sa = srv_a.state
    with kv.lock():
        _commit_running(sa)
        sa.save_executor_metadata(_meta("e1"))
        _stage_plan(sa)
        sa.save_task_status(_pending("j", 1, 0))
    done = _pending("j", 1, 0)
    done.completed.executor_id = "e1"
    done.completed.path = "/x"
    recovery_stats(reset=True)
    params = pb.PollWorkParams(
        metadata=_meta("e1"), can_accept_task=True, task_status=[done]
    )
    with pytest.raises(RuntimeError, match="owned by peer replica 'a'"):
        srv_b.PollWork(params)
    # the foreign completion was NOT folded — the owner's pending task is
    # untouched and no assignment happened on the redirecting replica
    assert sa.get_task_status("j", 1, 0).WhichOneof("status") is None
    assert ("j", 1, 0) not in srv_b.state._assigned
    stats = recovery_stats(reset=True)
    assert stats.get("ownership_redirected", 0) == 1, stats
    # the owner itself folds the same (idempotent) re-delivery fine
    result = srv_a.PollWork(
        pb.PollWorkParams(metadata=_meta("e1"), task_status=[done])
    )
    assert result is not None
    assert sa.get_task_status("j", 1, 0).WhichOneof("status") == "completed"


def test_get_job_status_carries_owner_hint_on_non_owners():
    kv = MemoryBackend()
    cfg = BallistaConfig({"ballista.scheduler.lease_ttl_s": "5"})
    srv_a = SchedulerServer(
        kv, config=cfg, replica_id="a", advertise_addr="127.0.0.1:7001"
    )
    srv_b = SchedulerServer(
        kv, config=cfg, replica_id="b", advertise_addr="127.0.0.1:7002"
    )
    with kv.lock():
        _commit_running(srv_a.state)
    # any replica answers with KV truth; non-owners add the owner's address
    res_b = srv_b.GetJobStatus(pb.GetJobStatusParams(job_id="j"))
    assert res_b.status.WhichOneof("status") == "running"
    assert res_b.owner_addr == "127.0.0.1:7001"
    res_a = srv_a.GetJobStatus(pb.GetJobStatusParams(job_id="j"))
    assert res_a.status.WhichOneof("status") == "running"
    assert res_a.owner_addr == ""


def test_queued_grace_sweep_fails_dead_planners_jobs_only():
    """A queued job whose planner replica heartbeats stays queued; once the
    heartbeat lapses AND the 2xTTL grace passes, a peer fails it with a CAS
    against the exact queued bytes (racing a resurrected planner's atomic
    commit, exactly one write lands)."""
    from ballista_tpu.ops.runtime import recovery_stats

    kv = MemoryBackend()
    cfg = BallistaConfig({"ballista.scheduler.lease_ttl_s": "0.05"})
    srv_a = SchedulerServer(
        kv, config=cfg, replica_id="a", advertise_addr="127.0.0.1:7001"
    )
    srv_b = SchedulerServer(
        kv, config=cfg, replica_id="b", advertise_addr="127.0.0.1:7002"
    )
    sa = srv_a.state
    with kv.lock():
        queued = pb.JobStatus()
        queued.queued.SetInParent()
        sa.save_job_metadata("jq", queued)
        sa.mark_job_planner("jq")
        sa.replica_heartbeat()
    seen = {}
    with kv.lock():
        assert srv_b._sweep_queued_grace_locked(seen) == 0
    assert "jq" not in seen  # planner heartbeating: no grace clock started
    time.sleep(0.12)  # replica a's heartbeat lapses
    with kv.lock():
        assert srv_b._sweep_queued_grace_locked(seen) == 0  # grace starts
    assert "jq" in seen
    time.sleep(0.12)  # 2xTTL grace elapses
    recovery_stats(reset=True)
    with kv.lock():
        assert srv_b._sweep_queued_grace_locked(seen) == 1
    st = srv_b.state.get_job_metadata("jq")
    assert st.WhichOneof("status") == "failed"
    assert "replica 'a'" in st.failed.error
    assert recovery_stats(reset=True).get("queued_grace_failed", 0) == 1
    # terminal: a later sweep has nothing left to do
    with kv.lock():
        assert srv_b._sweep_queued_grace_locked(seen) == 0


# -- acceptance e2e ----------------------------------------------------------

GROUP_SQL = (
    "select region, sum(amount) as s, count(*) as n from sales "
    "group by region order by region"
)
_SETTINGS = {"ballista.shuffle.partitions": "4"}


def _oracle(sales_table):
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.executor.runtime import StandaloneCluster

    cluster = StandaloneCluster(n_executors=2)
    try:
        ctx = BallistaContext(*cluster.scheduler_addr, settings=_SETTINGS)
        ctx.register_record_batches("sales", sales_table, n_partitions=4)
        out = ctx.sql(GROUP_SQL).collect()
        ctx.close()
        return out
    finally:
        cluster.shutdown()


def _submit_async(ctx, sql):
    """Run collect() on a worker thread; returns (thread, box, errors)."""
    box, errors = {}, []

    def run():
        try:
            box["out"] = ctx.sql(sql).collect()
        except Exception as e:  # surface in the main thread
            errors.append(e)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t, box, errors


def _wait_for(pred, timeout=10.0, what="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.02)
    pytest.fail(f"timed out waiting for {what}")


def test_three_replica_owner_kill_failover_bit_identical(sales_table):
    """ISSUE 20 acceptance: 3 replicas over one KV, the job's owner is
    killed mid-job (permanently), an idle peer adopts within the lease TTL
    via scoped restart recovery, and the job completes bit-identical to a
    single-scheduler fault-free oracle with zero task retries."""
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.executor.runtime import StandaloneCluster
    from ballista_tpu.ops.runtime import recovery_stats

    clean = _oracle(sales_table)
    cfg = BallistaConfig({"ballista.scheduler.lease_ttl_s": "0.3"})
    recovery_stats(reset=True)
    # no executors yet: the job is guaranteed mid-flight when the owner dies
    cluster = StandaloneCluster(n_executors=0, n_schedulers=3, config=cfg)
    try:
        ctx = BallistaContext(
            *cluster.scheduler_addr,
            settings=_SETTINGS,
            endpoints=cluster.scheduler_endpoints,
        )
        ctx.register_record_batches("sales", sales_table, n_partitions=4)
        t, box, errors = _submit_async(ctx, GROUP_SQL)
        s0 = cluster.scheduler_impls[0].state
        _wait_for(lambda: s0.owned_jobs(), what="replica 0 planning commit")
        job_id = s0.owned_jobs()[0]
        cluster.kill_scheduler(0)
        peers = cluster.scheduler_impls[1:]
        _wait_for(
            lambda: any(impl.state.owns_job(job_id) for impl in peers),
            what="a peer adopting the orphaned job",
        )
        for _ in range(2):
            cluster._spawn_executor()
        t.join(90)
        assert not t.is_alive(), "failover run never completed"
        assert not errors, errors
        ctx.close()
    finally:
        cluster.shutdown()
    stats = recovery_stats(reset=True)
    assert box["out"].equals(clean), (
        box["out"].to_pydict(), clean.to_pydict()
    )
    assert stats.get("lease_adopted", 0) >= 1, stats
    assert stats.get("restart_job_resumed", 0) >= 1, stats
    assert stats.get("task_retry", 0) == 0, stats


def test_paused_deposed_owner_late_writes_rejected_e2e(sales_table):
    """ISSUE 20 fencing acceptance: the owner pauses (a long GC pause —
    housekeeping stops renewing, the process stays alive), a peer adopts,
    and the revived owner's late writes are rejected whole: the adopted
    job completes uncorrupted, bit-identical to the oracle."""
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.executor.runtime import StandaloneCluster
    from ballista_tpu.ops.runtime import recovery_stats

    clean = _oracle(sales_table)
    cfg = BallistaConfig({"ballista.scheduler.lease_ttl_s": "0.2"})
    recovery_stats(reset=True)
    cluster = StandaloneCluster(n_executors=0, n_schedulers=2, config=cfg)
    try:
        ctx = BallistaContext(
            *cluster.scheduler_addr,
            settings=_SETTINGS,
            endpoints=cluster.scheduler_endpoints,
        )
        ctx.register_record_batches("sales", sales_table, n_partitions=4)
        t, box, errors = _submit_async(ctx, GROUP_SQL)
        impl0, impl1 = cluster.scheduler_impls
        _wait_for(lambda: impl0.state.owned_jobs(),
                  what="replica 0 planning commit")
        job_id = impl0.state.owned_jobs()[0]
        impl0.stop_housekeeping()  # the pause: renewals stop, process lives
        _wait_for(lambda: impl1.state.owns_job(job_id),
                  what="the peer adopting the paused owner's job")
        # the owner revives and writes as if it still owned the job
        stale = pb.JobStatus()
        stale.failed.error = "stale verdict from the paused owner"
        with cluster.kv.lock():
            assert impl0.state.save_job_metadata(job_id, stale) is False
        assert impl0.state.fence_rejected >= 1
        # no corruption: the adopter's running status survived the attempt
        assert (
            impl1.state.get_job_metadata(job_id).WhichOneof("status")
            == "running"
        )
        for _ in range(2):
            cluster._spawn_executor()
        t.join(90)
        assert not t.is_alive(), "adopted job never completed"
        assert not errors, errors
        # the job finished under the adopter, untouched by the stale write
        assert (
            impl1.state.get_job_metadata(job_id).WhichOneof("status")
            == "completed"
        )
        ctx.close()
    finally:
        cluster.shutdown()
    stats = recovery_stats(reset=True)
    assert box["out"].equals(clean), (
        box["out"].to_pydict(), clean.to_pydict()
    )
    assert stats.get("fence_rejected", 0) >= 1, stats
    assert stats.get("task_retry", 0) == 0, stats

"""Disaggregated shuffle tier + elastic executor fleet (ISSUE 15).

The invariant under test everywhere: with ballista.shuffle.tier=shared a
piece's home is a PATH, not a process — executor death after map completion
(and graceful scale-in at any time) completes the job with ZERO lineage
recomputes and ZERO task retries, bit-identical to the local tier and to a
fixed fleet. Torn storage writes (shuffle.store chaos) degrade to the
normal retry/lineage ladder, never to a wrong answer; the autoscaler grows
the fleet against the cost-model-predicted backlog and drains it back when
idle.
"""

import os
import time

import numpy as np
import pyarrow as pa
import pytest

from ballista_tpu.config import BallistaConfig
from ballista_tpu.proto import ballista_pb2 as pb
from ballista_tpu.ops.runtime import (
    fleet_stats,
    recovery_stats,
    shuffle_tier_stats,
)

GROUP_SQL = (
    "select region, sum(amount) as s from sales group by region order by region"
)


@pytest.fixture
def shared_dir(tmp_path):
    d = tmp_path / "shuffle-store"
    d.mkdir()
    return str(d)


def _shared_settings(shared_dir, **over):
    base = {
        "ballista.shuffle.partitions": "4",
        "ballista.cache.results": "false",
        "ballista.shuffle.tier": "shared",
        "ballista.shuffle.dir": shared_dir,
    }
    base.update(over)
    return base


def _local_settings(**over):
    base = {
        "ballista.shuffle.partitions": "4",
        "ballista.cache.results": "false",
    }
    base.update(over)
    return base


# -- config -------------------------------------------------------------------

def test_shared_tier_requires_dir():
    cfg = BallistaConfig({"ballista.shuffle.tier": "shared"})
    with pytest.raises(ValueError, match="ballista.shuffle.dir"):
        cfg.shuffle_storage_root()
    assert BallistaConfig().shuffle_storage_root() == ""
    with pytest.raises(ValueError, match="unknown shuffle tier"):
        BallistaConfig({"ballista.shuffle.tier": "s3"}).shuffle_tier()


# -- writer: shared publish layout + atomic torn-write ------------------------

def _writer(job="jx", stage=2, partitions=2):
    from ballista_tpu.datasource import MemoryTableSource
    from ballista_tpu.distributed.stages import ShuffleWriterExec
    from ballista_tpu.physical.expr import ColumnExpr
    from ballista_tpu.physical.plan import Partitioning
    from ballista_tpu.physical.scan import MemoryScanExec

    t = pa.table({
        "g": pa.array([1, 2, 3, 4, 1, 2], type=pa.int64()),
        "v": pa.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0]),
    })
    scan = MemoryScanExec(MemoryTableSource.from_table(t))
    part = Partitioning.hash([ColumnExpr("g", 0)], partitions)
    return ShuffleWriterExec(job, stage, scan, part)


def test_shared_publish_layout_and_counters(shared_dir, tmp_path):
    from ballista_tpu.physical.plan import TaskContext

    w = _writer()
    ctx = TaskContext(
        config=BallistaConfig(_shared_settings(shared_dir)),
        work_dir=str(tmp_path / "work"),
        job_id="jx",
    )
    shuffle_tier_stats(reset=True)
    stats = w.execute_shuffle_write(0, ctx)
    assert stats.num_rows == 6
    base = os.path.join(shared_dir, "jx", "2", "0")
    pieces = sorted(os.listdir(base))
    assert pieces == ["0.arrow", "1.arrow"], pieces
    # nothing under the work dir, no tmp residue in storage
    assert not os.path.exists(os.path.join(str(tmp_path / "work"), "jx"))
    assert not [p for p in pieces if ".tmp-" in p]
    st = shuffle_tier_stats(reset=True)
    assert st.get("storage_publish") == 1, st


def test_shuffle_store_write_chaos_tears_publish_atomically(shared_dir, tmp_path):
    """A shuffle.store WRITE verdict fires after the temp pieces closed and
    before any replace: the task attempt fails with NOTHING published (no
    piece, no tmp residue) — degrading to the normal retry ladder."""
    from ballista_tpu.physical.plan import TaskContext
    from ballista_tpu.utils.chaos import ChaosInjected

    w = _writer()
    ctx = TaskContext(
        config=BallistaConfig(_shared_settings(
            shared_dir,
            **{
                "ballista.chaos.rate": "1.0",
                "ballista.chaos.seed": "1",
                "ballista.chaos.sites": "shuffle.store",
            },
        )),
        work_dir=str(tmp_path / "work"),
        job_id="jx",
    )
    shuffle_tier_stats(reset=True)
    with pytest.raises(ChaosInjected):
        w.execute_shuffle_write(0, ctx)
    base = os.path.join(shared_dir, "jx", "2", "0")
    published = os.listdir(base) if os.path.isdir(base) else []
    assert published == [], published
    st = shuffle_tier_stats(reset=True)
    assert st.get("storage_publish_torn") == 1, st


# -- reader: storage-first ladder --------------------------------------------

def _reader_for(base, schema, host="", port=0):
    from ballista_tpu.distributed.stages import (
        ShuffleLocation,
        ShuffleReaderExec,
    )

    loc = ShuffleLocation(
        "dead-exec", host, port, base,
        stage_id=2, map_partition=0, storage_uri=base,
    )
    return ShuffleReaderExec([loc], schema, 2)


def test_reader_resolves_storage_first_without_any_peer(shared_dir, tmp_path):
    """A storage-homed piece reads straight from the mount: no fetcher, no
    live producer, no work-dir copy — executor death changed nothing."""
    from ballista_tpu.physical.plan import TaskContext

    w = _writer()
    wctx = TaskContext(
        config=BallistaConfig(_shared_settings(shared_dir)),
        work_dir=str(tmp_path / "work"), job_id="jx",
    )
    w.execute_shuffle_write(0, wctx)
    base = os.path.join(shared_dir, "jx", "2", "0")
    reader = _reader_for(base, w.schema())
    rctx = TaskContext(
        config=BallistaConfig(_shared_settings(shared_dir)),
        work_dir=str(tmp_path / "work2"), job_id="jy",
        shuffle_fetcher=None,
    )
    shuffle_tier_stats(reset=True)
    rows = sum(b.num_rows for b in reader.execute(0, rctx))
    rows += sum(b.num_rows for b in reader.execute(1, rctx))
    assert rows == 6
    st = shuffle_tier_stats(reset=True)
    assert st.get("storage_fetch") == 2, st
    assert "storage_fallback_peer" not in st, st


def test_reader_missing_storage_piece_degrades_to_lineage(shared_dir, tmp_path):
    """A storage-homed piece that is NOT in storage (torn away, GC'd) and
    has no live peer surfaces as ShuffleFetchError naming the producing map
    task — the fetch_failed -> lineage-recompute ladder."""
    from ballista_tpu.errors import ShuffleFetchError
    from ballista_tpu.physical.plan import TaskContext

    base = os.path.join(shared_dir, "jx", "2", "0")  # never written
    schema = pa.schema([("g", pa.int64())])
    reader = _reader_for(base, schema)
    rctx = TaskContext(
        config=BallistaConfig(_shared_settings(shared_dir)),
        work_dir=str(tmp_path / "work"), job_id="jy",
    )
    shuffle_tier_stats(reset=True)
    with pytest.raises(ShuffleFetchError) as ei:
        list(reader.execute(0, rctx))
    assert ei.value.stage_id == 2 and ei.value.map_partition == 0
    st = shuffle_tier_stats(reset=True)
    assert st.get("storage_fallback_peer") == 1, st


def test_reader_read_chaos_falls_back_then_recovers_lineage(shared_dir, tmp_path):
    """A shuffle.store READ verdict makes a published piece unreadable for
    this attempt: with no peer the reader names the lost map task
    (lineage); a RETRIED attempt (fresh chaos key) reads it fine."""
    from ballista_tpu.errors import ShuffleFetchError
    from ballista_tpu.physical.plan import TaskContext
    from ballista_tpu.utils.chaos import ChaosInjector

    w = _writer()
    wctx = TaskContext(
        config=BallistaConfig(_shared_settings(shared_dir)),
        work_dir=str(tmp_path / "work"), job_id="jx",
    )
    w.execute_shuffle_write(0, wctx)
    base = os.path.join(shared_dir, "jx", "2", "0")
    # seed where attempt 0's read verdict is torn and attempt 1's is not
    seed = None
    for cand in range(500):
        inj = ChaosInjector(cand, 0.5, sites=("shuffle.store",))
        if inj.should_inject(
            "shuffle.store", "r2/0/piece0@a0"
        ) and not inj.should_inject("shuffle.store", "r2/0/piece0@a1"):
            seed = cand
            break
    assert seed is not None
    reader = _reader_for(base, w.schema())
    chaos_settings = _shared_settings(
        shared_dir,
        **{
            "ballista.chaos.rate": "0.5",
            "ballista.chaos.seed": str(seed),
            "ballista.chaos.sites": "shuffle.store",
        },
    )
    from ballista_tpu.physical.plan import TaskContext as TC

    rctx0 = TC(config=BallistaConfig(chaos_settings),
               work_dir=str(tmp_path / "w0"), job_id="jy", attempt=0)
    with pytest.raises(ShuffleFetchError):
        list(reader.execute(0, rctx0))
    rctx1 = TC(config=BallistaConfig(chaos_settings),
               work_dir=str(tmp_path / "w1"), job_id="jy", attempt=1)
    rows = sum(b.num_rows for b in reader.execute(0, rctx1))
    assert rows > 0


# -- scheduler: storage-homed outputs survive their executor ------------------

def _state(config=None):
    from ballista_tpu.scheduler.kv import MemoryBackend
    from ballista_tpu.scheduler.state import SchedulerState

    return SchedulerState(
        MemoryBackend(), "elastic",
        config=config or BallistaConfig({"ballista.tpu.cost_model_dir": ""}),
    )


def _completed_task(job, stage, part, executor, storage_uri=""):
    t = pb.TaskStatus()
    t.partition_id.job_id = job
    t.partition_id.stage_id = stage
    t.partition_id.partition_id = part
    t.completed.executor_id = executor
    t.completed.path = f"/x/{job}/{stage}/{part}"
    if storage_uri:
        t.completed.storage_uri = storage_uri
    return t


def test_reset_lost_tasks_keeps_storage_homed_outputs():
    """The tentpole's core scheduler rule: a COMPLETED task whose output is
    storage-homed survives its executor's death — no requeue, no retry
    budget consumed, no downstream invalidation. The work-dir sibling on
    the same dead executor still requeues (the local-tier contract)."""
    s = _state()
    running = pb.JobStatus()
    running.running.SetInParent()
    s.save_job_metadata("j", running)
    s.save_task_status(_completed_task("j", 1, 0, "dead", storage_uri="/s/j/1/0"))
    s.save_task_status(_completed_task("j", 1, 1, "dead"))
    recovery_stats(reset=True)
    reset = s.reset_lost_tasks()  # nobody holds a lease: "dead" is dead
    assert reset == 1, reset
    stats = recovery_stats(reset=True)
    assert stats.get("storage_home_retained") == 1, stats
    assert stats.get("task_retry", 0) == 1, stats
    kept = s.get_task_status("j", 1, 0)
    assert kept.WhichOneof("status") == "completed"
    requeued = s.get_task_status("j", 1, 1)
    assert requeued.WhichOneof("status") is None and requeued.attempt == 1


def test_bound_plan_carries_storage_uri():
    """Locations bound into downstream stage plans carry the path-home, so
    the executing reader resolves storage-first even when the producer's
    metadata is long gone."""
    s = _state()
    from ballista_tpu.distributed.stages import (
        ShuffleReaderExec,
        UnresolvedShuffleExec,
    )

    schema = pa.schema([("g", pa.int64())])
    s.save_stage_plan("j", 2, UnresolvedShuffleExec(1, schema, 2))
    s.save_task_status(_completed_task("j", 1, 0, "gone", storage_uri="/s/j/1/0"))
    idx = s._ensure_task_index()
    bound = s._bound_stage_plan("j", 2, idx)
    assert isinstance(bound, ShuffleReaderExec)
    assert bound.locations[0].storage_uri == "/s/j/1/0"
    assert bound.locations[0].host == ""  # producer gone; storage is home


def test_result_cache_liveness_skips_storage_homed_locations():
    """A cached entry whose partitions are storage-homed stays servable
    after the producing executor retires (the dead-lease invalidation only
    guards work-dir locations)."""
    s = _state()
    completed = pb.CompletedJob()
    pl = completed.partition_location.add()
    pl.executor_meta.id = "retired"
    pl.path = "/s/j/9/0"
    pl.storage_uri = "/s/j/9/0"
    assert s.result_cache_put("fp-storage", completed)
    hit = s.result_cache_lookup("fp-storage")
    assert hit is not None and hit.partition_location[0].storage_uri
    # contrast: a work-dir entry from a dead executor invalidates
    completed2 = pb.CompletedJob()
    pl2 = completed2.partition_location.add()
    pl2.executor_meta.id = "retired"
    pl2.path = "/w/j/9/0"
    assert s.result_cache_put("fp-workdir", completed2)
    assert s.result_cache_lookup("fp-workdir") is None


def test_predicted_backlog_seconds_scales_with_pending():
    """The autoscaling signal: warm task.run rates multiply into the
    pending count; never-observed stages contribute the small cold prior;
    terminal jobs contribute nothing."""
    from ballista_tpu.scheduler.state import BACKLOG_COLD_TASK_SECONDS

    s = _state()
    running = pb.JobStatus()
    running.running.SetInParent()
    s.save_job_metadata("j", running)
    from ballista_tpu.physical.basic import EmptyExec

    schema = pa.schema([("g", pa.int64())])
    s.save_stage_plan("j", 1, EmptyExec(False, schema))
    for p in range(4):
        t = pb.TaskStatus()
        t.partition_id.job_id = "j"
        t.partition_id.stage_id = 1
        t.partition_id.partition_id = p
        s.save_task_status(t)
    cold = s.predicted_backlog_seconds()
    assert cold == pytest.approx(4 * BACKLOG_COLD_TASK_SECONDS)
    # warm the rate: 200ms per task of this stage shape
    for _ in range(8):
        s._observe_task_run("j", 1, 0.2)
    warm = s.predicted_backlog_seconds()
    assert warm == pytest.approx(4 * 0.2, rel=0.2)
    # a failed job's leftover pending tasks stop counting
    failed = pb.JobStatus()
    failed.failed.error = "x"
    s.save_job_metadata("j", failed)
    assert s.predicted_backlog_seconds() == 0.0


# -- e2e: executor death after map completion is a non-event ------------------

def _run_job_kill_owner_prefetch(sales_table, settings):
    """Submit the 2-stage group-by, wait for COMPLETION, then kill an
    executor holding result partitions (and map outputs) — totally
    (heartbeat AND data plane) — BEFORE anything is fetched. Returns
    (result table, recovery stats). On the local tier this is the
    ReportLostPartition-restart scenario; on the shared tier the fetch
    reads storage and nothing restarts."""
    import ballista_tpu.scheduler.state as state_mod
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.executor.runtime import StandaloneCluster

    cluster = StandaloneCluster(n_executors=2)
    old_lease = state_mod.EXECUTOR_LEASE_SECS
    state_mod.EXECUTOR_LEASE_SECS = 1.0
    cluster.scheduler_impl.lost_task_check_interval = 0.3
    recovery_stats(reset=True)
    try:
        ctx = BallistaContext(*cluster.scheduler_addr, settings=settings)
        ctx.register_record_batches("sales", sales_table, n_partitions=4)
        plan = ctx.sql(GROUP_SQL).logical_plan()
        job_id = ctx.submit(plan)
        status = ctx._wait_for_job(job_id, timeout=60.0)
        owners = {
            pl.executor_meta.id
            for pl in status.completed.partition_location
        }
        victim = next(ex for ex in cluster.executors if ex.id in owners)
        victim.stop()
        out = ctx._collect_results(job_id, plan.schema(), timeout=120.0)
        stats = recovery_stats(reset=True)
        ctx.close()
        return out, stats
    finally:
        state_mod.EXECUTOR_LEASE_SECS = old_lease
        cluster.shutdown()


def test_executor_death_after_completion_is_a_nonevent_on_shared_tier(
    sales_table, shared_dir
):
    """ISSUE 15 acceptance: the SAME kill-the-result-owner-before-fetch
    harness that forces a ReportLostPartition restart on the local tier
    (nonzero restarts + task retries, pinned below) completes on the
    shared tier with ZERO recovery events of any kind — the dead
    executor's pieces kept their storage home and the client read them
    from the mount — and results are bit-identical across the tiers."""
    shuffle_tier_stats(reset=True)
    shared_out, shared_stats = _run_job_kill_owner_prefetch(
        sales_table, _shared_settings(shared_dir)
    )
    tier = shuffle_tier_stats(reset=True)
    local_out, local_stats = _run_job_kill_owner_prefetch(
        sales_table, _local_settings()
    )
    assert shared_out.equals(local_out), (
        shared_out.to_pydict(), local_out.to_pydict(),
    )
    assert shared_out.column("s").to_pylist() == [120.0, 40.0, 145.0]
    # shared tier: the non-event — no restart, no retry, no lineage
    for event in (
        "task_retry", "map_recomputed", "fetch_failed", "lost_task_reset",
        "downstream_invalidated", "result_partition_restarted",
        "completed_job_restarted", "result_fetch_restarted",
    ):
        assert shared_stats.get(event, 0) == 0, (event, shared_stats)
    assert tier.get("storage_publish", 0) >= 1, tier
    assert tier.get("client_storage_fetch", 0) >= 1, tier
    # local tier, same harness: the loss IS an event (fetch-time restart
    # through lineage, consuming retries)
    assert local_stats.get("result_partition_restarted", 0) > 0, local_stats
    assert local_stats.get("task_retry", 0) > 0, local_stats


def test_executor_death_mid_job_shared_tier_zero_lineage_recompute(
    sales_table, shared_dir
):
    """Executor killed right after its MAP stage completed, while reduces
    run: on the shared tier the surviving reduces read the dead executor's
    map pieces straight from storage — ZERO lineage recomputes (no
    fetch_failed, no map recompute, no downstream invalidation) and the
    completed map outputs are retained (storage_home_retained), with only
    the victim's genuinely in-flight reduces retrying (no tier can save
    running work). The local-tier contrast — nonzero lineage events on
    this exact harness — is pinned by test_fault_tolerance's
    test_end_to_end_recovery_after_executor_death_with_lost_outputs."""
    import ballista_tpu.scheduler.state as state_mod
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.executor.runtime import StandaloneCluster

    cluster = StandaloneCluster(n_executors=2)
    old_lease = state_mod.EXECUTOR_LEASE_SECS
    state_mod.EXECUTOR_LEASE_SECS = 1.0
    cluster.scheduler_impl.lost_task_check_interval = 0.3
    recovery_stats(reset=True)
    shuffle_tier_stats(reset=True)
    try:
        ctx = BallistaContext(
            *cluster.scheduler_addr, settings=_shared_settings(shared_dir)
        )
        ctx.register_record_batches("sales", sales_table, n_partitions=4)
        plan = ctx.sql(GROUP_SQL).logical_plan()
        job_id = ctx.submit(plan)
        state = cluster.scheduler_impl.state
        deadline = time.time() + 60
        stage1 = []
        while time.time() < deadline:
            tasks = state.get_job_tasks(job_id)
            if tasks:
                first = min(t.partition_id.stage_id for t in tasks)
                stage1 = [t for t in tasks if t.partition_id.stage_id == first]
                if stage1 and all(
                    t.WhichOneof("status") == "completed" for t in stage1
                ):
                    break
            time.sleep(0.02)
        else:
            pytest.fail("map stage did not complete in time")
        assert all(t.completed.storage_uri for t in stage1), (
            "map outputs not storage-homed"
        )
        owners = {t.completed.executor_id for t in stage1}
        victim = next(ex for ex in cluster.executors if ex.id in owners)
        victim.stop()
        out = ctx._collect_results(job_id, plan.schema(), timeout=120.0)
        assert out.column("s").to_pylist() == [120.0, 40.0, 145.0]
        stats = recovery_stats(reset=True)
        tier = shuffle_tier_stats(reset=True)
        # ZERO lineage recomputation: the map outputs never needed it
        assert stats.get("fetch_failed", 0) == 0, stats
        assert stats.get("map_recomputed", 0) == 0, stats
        assert stats.get("downstream_invalidated", 0) == 0, stats
        assert stats.get("storage_home_retained", 0) >= 1, stats
        assert tier.get("storage_fetch", 0) >= 1, tier
        ctx.close()
    finally:
        state_mod.EXECUTOR_LEASE_SECS = old_lease
        cluster.shutdown()


# -- e2e: graceful scale-in during a running job ------------------------------

def test_scale_in_during_running_job_bit_identical_zero_retries(
    sales_table, shared_dir
):
    """ISSUE 15 acceptance: gracefully retiring an executor MID-JOB on the
    shared tier (the autoscaler's drain -> stop -> remove mechanism,
    chaos-armed on fleet.scale) is invisible to the job: results are
    bit-identical to a fixed-fleet run and the recovery counters show zero
    task retries — the retiree finished its in-flight work and its
    completed outputs stayed readable from storage."""
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.executor.runtime import StandaloneCluster

    settings = _shared_settings(shared_dir)
    # fixed-fleet reference
    cluster = StandaloneCluster(n_executors=2)
    try:
        ctx = BallistaContext(*cluster.scheduler_addr, settings=settings)
        ctx.register_record_batches("sales", sales_table, n_partitions=4)
        ref = ctx.sql(GROUP_SQL).collect()
        ctx.close()
    finally:
        cluster.shutdown()

    # elastic run: retire one executor the moment the job is mid-flight.
    # fleet.scale chaos is ARMED (autoscaler evaluations can be torn);
    # the explicit scale_in_one drives the same drain machinery
    # deterministically while the job runs.
    fleet_stats(reset=True)
    recovery_stats(reset=True)
    cluster = StandaloneCluster(
        n_executors=2,
        config=BallistaConfig({
            "ballista.fleet.min": "1",
            "ballista.fleet.max": "2",
            "ballista.fleet.interval_s": "0.1",
            "ballista.chaos.rate": "0.3",
            "ballista.chaos.seed": "7",
            "ballista.chaos.sites": "fleet.scale",
        }),
    )
    try:
        shared_dir2 = os.path.join(shared_dir, "scalein")
        os.makedirs(shared_dir2, exist_ok=True)
        settings2 = _shared_settings(shared_dir2)
        ctx = BallistaContext(*cluster.scheduler_addr, settings=settings2)
        ctx.register_record_batches("sales", sales_table, n_partitions=4)
        plan = ctx.sql(GROUP_SQL).logical_plan()
        job_id = ctx.submit(plan)
        # wait until the job is actually running (some task started), then
        # scale in while it is in flight
        state = cluster.scheduler_impl.state
        deadline = time.time() + 60
        while time.time() < deadline:
            tasks = state.get_job_tasks(job_id)
            if tasks and any(
                t.WhichOneof("status") in ("running", "completed")
                for t in tasks
            ):
                break
            time.sleep(0.01)
        assert cluster.scale_in_one(timeout=60.0), "scale-in declined"
        status = ctx._wait_for_job(job_id, timeout=120.0)
        tables = [
            ctx._fetch_partition(loc)
            for loc in status.completed.partition_location
        ]
        out = pa.concat_tables(tables).cast(plan.schema())
        ctx.close()
    finally:
        cluster.shutdown()
    assert out.equals(ref), (out.to_pydict(), ref.to_pydict())
    stats = recovery_stats(reset=True)
    assert stats.get("task_retry", 0) == 0, stats
    assert stats.get("orphan_reassigned", 0) == 0, stats
    fl = fleet_stats(reset=True)
    assert fl.get("scale_down", 0) >= 1, fl
    assert fl.get("drain_completed", 0) >= 1, fl
    assert cluster.fleet_size() == 1


# -- e2e: autoscaler grows under backlog, drains when idle --------------------

def test_autoscaler_grows_under_backlog_and_drains_idle(shared_dir):
    """The closed loop: a burst of concurrent jobs registers as predicted
    backlog, the fleet grows toward ballista.fleet.max, every job
    completes, and the idle fleet drains back to ballista.fleet.min with
    clean drains (zero retries)."""
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.executor.runtime import StandaloneCluster

    rng = np.random.default_rng(5)
    n = 20_000
    table = pa.table({
        "g": pa.array(rng.integers(0, 9, n), type=pa.int64()),
        "v": pa.array(np.round(rng.uniform(-100, 100, n), 2)),
    })
    sql = "select g, sum(v) as s, count(*) as c from t group by g order by g"
    fleet_stats(reset=True)
    recovery_stats(reset=True)
    cluster = StandaloneCluster(
        n_executors=1,
        config=BallistaConfig({
            "ballista.fleet.min": "1",
            "ballista.fleet.max": "3",
            "ballista.fleet.interval_s": "0.1",
            "ballista.fleet.target_backlog_s": "0.05",
        }),
    )
    try:
        ctx = BallistaContext(
            *cluster.scheduler_addr,
            settings=_shared_settings(shared_dir, **{
                "ballista.shuffle.partitions": "8",
            }),
        )
        ctx.register_record_batches("t", table, n_partitions=8)
        ref = ctx.sql(sql).collect()
        jobs = [ctx.submit(ctx.sql(sql).logical_plan()) for _ in range(4)]
        peak = cluster.fleet_size()
        deadline = time.time() + 60
        statuses = []
        while time.time() < deadline:
            peak = max(peak, cluster.fleet_size())
            statuses = [
                ctx._client.get_job_status(
                    pb.GetJobStatusParams(job_id=j)
                ).status
                for j in jobs
            ]
            if all(
                s.WhichOneof("status") in ("completed", "failed")
                for s in statuses
            ):
                break
            time.sleep(0.05)
        assert all(
            s.WhichOneof("status") == "completed" for s in statuses
        ), [s.WhichOneof("status") for s in statuses]
        for j in jobs:
            got = ctx._collect_results(j, ref.schema)
            assert got.equals(ref), j
        # idle: the fleet must drain back to min via graceful drains
        deadline = time.time() + 30
        while time.time() < deadline and cluster.fleet_size() > 1:
            time.sleep(0.1)
        assert cluster.fleet_size() == 1
        ctx.close()
    finally:
        cluster.shutdown()
    fl = fleet_stats(reset=True)
    assert fl.get("scale_up", 0) >= 1, fl
    assert fl.get("scale_down", 0) >= 1, fl
    assert fl.get("drain_completed", 0) >= fl.get("scale_down", 0), fl
    assert peak > 1, f"fleet never grew (peak {peak})"
    stats = recovery_stats(reset=True)
    assert stats.get("task_retry", 0) == 0, stats


def test_fleet_scale_chaos_skips_decisions():
    """A fleet.scale verdict tears the scale decision BEFORE any executor
    is touched: the fleet keeps its size that evaluation and the skip is
    counted, never silent."""
    from ballista_tpu.executor.runtime import StandaloneCluster
    from ballista_tpu.utils.chaos import ChaosInjector

    # seed whose FIRST decision verdict is torn (sequence-keyed)
    seed = next(
        s for s in range(200)
        if ChaosInjector(s, 1.0, sites=("fleet.scale",)).should_inject(
            "fleet.scale", "scale1"
        )
    )
    fleet_stats(reset=True)
    cluster = StandaloneCluster(
        n_executors=2,
        config=BallistaConfig({
            "ballista.fleet.min": "1",
            "ballista.fleet.max": "2",
            # interval long enough that only explicit evaluations run
            "ballista.fleet.interval_s": "3600",
            "ballista.chaos.rate": "1.0",
            "ballista.chaos.seed": str(seed),
            "ballista.chaos.sites": "fleet.scale",
        }),
    )
    try:
        # idle 2-executor cluster above min: the decision is scale-in,
        # torn by chaos -> no action
        assert cluster.autoscale_once() == 0
        assert cluster.fleet_size() == 2
        fl = fleet_stats(reset=True)
        assert fl.get("scale_chaos_skipped") == 1, fl
        assert fl.get("scale_down", 0) == 0, fl
    finally:
        cluster.shutdown()


# -- security + GC regressions (review findings) ------------------------------

def test_flight_execute_partition_ignores_peer_shuffle_settings(
    sales_table, tmp_path
):
    """Review regression: an unauthenticated Flight peer's per-request
    settings must NOT steer the shuffle WRITE home — the tier/dir come
    from the EXECUTOR's own config (like the scan-root allowlist), so
    ExecutePartition cannot publish .arrow files to an arbitrary host
    path. The hostile settings are simply overridden: the write lands in
    the work dir and the attacker-named directory stays untouched."""
    import socket
    import threading

    from ballista_tpu.client.flight import BallistaClient
    from ballista_tpu.engine import ExecutionContext
    from ballista_tpu.executor.flight_service import BallistaFlightService

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    work = tmp_path / "work"
    work.mkdir()
    svc = BallistaFlightService(
        f"grpc://0.0.0.0:{port}", str(work), BallistaConfig()
    )
    threading.Thread(target=svc.serve, daemon=True).start()
    try:
        ctx = ExecutionContext()
        ctx.register_record_batches("sales", sales_table, n_partitions=1)
        from ballista_tpu.logical import col, functions as F

        df = ctx.table("sales").aggregate([], [F.sum(col("amount")).alias("s")])
        physical = ctx.create_physical_plan(df.logical_plan())
        evil = str(tmp_path / "exfil")
        client = BallistaClient("127.0.0.1", port)
        results = client.execute_partition(
            "jobsec", 1, [0], physical,
            settings={
                "ballista.shuffle.tier": "shared",
                "ballista.shuffle.dir": evil,
            },
        )
        client.close()
        path, stats = results[0]
        assert stats.num_rows == 1
        assert path.startswith(str(work)), path
        assert not os.path.exists(evil), "peer settings steered the write"
    finally:
        svc.shutdown()


def test_gc_sweeps_shared_storage_root(tmp_path):
    """Review regression: the shuffle TTL sweep covers the executor's
    configured shared storage root beside its work dir — without it the
    shared mount grows without bound (no other component owns the
    pieces)."""
    from ballista_tpu.executor.execution_loop import PollLoop
    from ballista_tpu.scheduler.rpc import SchedulerGrpcClient

    work = tmp_path / "work"
    storage = tmp_path / "storage"
    for root in (work, storage):
        (root / "oldjob" / "1" / "0").mkdir(parents=True)
        (root / "oldjob" / "1" / "0" / "0.arrow").write_bytes(b"x")
    old = time.time() - 7200
    for root in (work, storage):
        os.utime(root / "oldjob", (old, old))
    loop = PollLoop(
        SchedulerGrpcClient("127.0.0.1", 1),
        pb.ExecutorMetadata(id="gc", host="h", port=1),
        str(work),
        config=BallistaConfig(_shared_settings(str(storage))),
    )
    loop.shuffle_ttl_seconds = 3600.0
    removed = loop.gc_work_dir()
    assert removed == 2, removed
    assert not (work / "oldjob").exists()
    assert not (storage / "oldjob").exists()


def test_executor_pinned_tier_ignores_per_job_redirection(sales_table, tmp_path):
    """Review regression (scheduler-dispatch path): an executor whose OWN
    config pins a shuffle tier keeps it — per-job client settings cannot
    redirect the os.replace publish to a client-chosen host path (the
    data_roots discipline applied to writes). An UNCONFIGURED executor
    still honors the per-job opt-in (every other test in this file)."""
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.executor.runtime import StandaloneCluster

    pinned = tmp_path / "pinned-store"
    pinned.mkdir()
    evil = tmp_path / "exfil"
    cluster = StandaloneCluster(
        n_executors=1,
        config=BallistaConfig({
            "ballista.shuffle.tier": "shared",
            "ballista.shuffle.dir": str(pinned),
        }),
    )
    try:
        ctx = BallistaContext(
            *cluster.scheduler_addr,
            settings={
                "ballista.shuffle.partitions": "2",
                "ballista.cache.results": "false",
                # hostile per-job redirection: must be ignored by the
                # pinned executor (reads still resolve via the PINNED root
                # the scheduler's storage_uri records point into)
                "ballista.shuffle.tier": "shared",
                "ballista.shuffle.dir": str(evil),
            },
        )
        ctx.register_record_batches("sales", sales_table, n_partitions=2)
        out = ctx.sql(GROUP_SQL).collect()
        assert out.column("s").to_pylist() == [120.0, 40.0, 145.0]
        assert not evil.exists(), "per-job settings steered the publish"
        assert os.listdir(pinned), "pinned storage root never used"
        ctx.close()
    finally:
        cluster.shutdown()

"""Shared-scan multi-query execution (ISSUE 13): one upload, one launch,
N queries.

The invariant under test everywhere: a batched execution is BIT-IDENTICAL
to solo execution for every member query — same backend, same to_pylist —
whatever the batch composition, the evidence gate's verdict, chaos at the
formation site, or a member's (or executor's) mid-batch death. Counters
prove the sharing actually happened (batches_formed / batched_stages /
uploads_saved / launches_saved), and every decline is visible, never
silent.

Determinism harness: clusters start with ZERO executors, the distinct
queries are submitted concurrently and PLAN while nothing can pull work,
then one executor starts — so every compatible stage task is co-pending at
first dispatch and batch formation is deterministic rather than a race.
"""

import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from ballista_tpu.client import BallistaContext
from ballista_tpu.config import BallistaConfig
from ballista_tpu.executor.runtime import BallistaExecutor, StandaloneCluster
from ballista_tpu.ops import costmodel
from ballista_tpu.ops.runtime import (
    recovery_stats,
    routing_stats,
    shared_scan_stats,
)
from ballista_tpu.utils.chaos import ChaosInjector

QUERIES = [
    "select g, sum(v) as s, count(*) as c from t group by g order by g",
    "select g, min(q) as mn, max(q) as mx from t where v > 0 "
    "group by g order by g",
    "select g, sum(q) as sq from t where q < 30 group by g order by g",
]
# device column `s` is a STRING filter input: its stage grows a per-stage
# dictionary, so it must never join a shared upload (string GROUP keys —
# `g` above — stay host-side and batch fine)
STRING_FILTER_QUERY = (
    "select g, count(*) as c from t where s <> 'x1' group by g order by g"
)


@pytest.fixture(scope="module")
def table_path(tmp_path_factory):
    rng = np.random.default_rng(42)
    n = 40_000
    t = pa.table({
        "g": pa.array([f"k{v}" for v in rng.integers(0, 6, n)]),
        "s": pa.array([f"x{v}" for v in rng.integers(0, 4, n)]),
        "v": pa.array(np.round(rng.uniform(-100, 100, n), 2)),
        "q": pa.array(rng.integers(1, 50, n), type=pa.int64()),
        "d": pa.array(
            rng.integers(8000, 12000, n), type=pa.int32()
        ).cast(pa.date32()),
    })
    path = str(tmp_path_factory.mktemp("sharedscan") / "t.parquet")
    pq.write_table(t, path)
    return path


def _client_settings(**over):
    base = {
        "ballista.executor.backend": "tpu",
        "ballista.cache.results": "false",
        "ballista.shuffle.partitions": "2",
        # the scan-per-query regime shared-scan exists for: with device
        # residency on, a warm member rightly degrades to its resident solo
        # run (pinned by test_resident_members_degrade_to_solo below) and
        # repeated suite queries would never batch
        "ballista.tpu.device_cache": "false",
    }
    base.update(over)
    return base


def _run_sequential(path, queries, client_settings=None, cluster_config=None):
    """Reference harness: one client, queries one at a time (nothing can
    co-pend, so nothing batches)."""
    cluster = StandaloneCluster(n_executors=1, config=cluster_config)
    try:
        ctx = BallistaContext(
            *cluster.scheduler_addr,
            settings=client_settings or _client_settings(),
        )
        ctx.register_parquet("t", path)
        out = [ctx.sql(q).collect().to_pydict() for q in queries]
        ctx.close()
        return out
    finally:
        cluster.shutdown()


def _run_concurrent(
    path, queries, client_settings=None, cluster_config=None,
    per_query_settings=None, plan_delay=1.5, executors=1,
    mid_flight=None, join_timeout=120,
):
    """Deterministic-batching harness: submit every query concurrently
    against a cluster with NO executors, wait for planning, then start the
    executor(s) — all compatible stage tasks are co-pending at first
    dispatch. `per_query_settings[i]` overlays query i's client settings;
    `mid_flight(cluster)` runs shortly after the executors start (executor
    -death injection)."""
    cluster = StandaloneCluster(n_executors=0, config=cluster_config)
    results = [None] * len(queries)
    errors = []
    try:
        def submit(i):
            try:
                settings = dict(client_settings or _client_settings())
                if per_query_settings and per_query_settings[i]:
                    settings.update(per_query_settings[i])
                c = BallistaContext(*cluster.scheduler_addr, settings=settings)
                c.register_parquet("t", path)
                results[i] = c.sql(queries[i]).collect().to_pydict()
                c.close()
            except Exception as e:  # surfaced by the caller's assert
                errors.append(f"q{i}: {e!r}")

        threads = [
            threading.Thread(target=submit, args=(i,))
            for i in range(len(queries))
        ]
        for th in threads:
            th.start()
        time.sleep(plan_delay)
        for i in range(executors):
            ex = BallistaExecutor(
                "127.0.0.1", cluster.port,
                config=cluster.config, executor_id=f"late-{i}",
            )
            ex.start()
            cluster.executors.append(ex)
        if mid_flight is not None:
            mid_flight(cluster)
        for th in threads:
            th.join(join_timeout)
        alive = [th for th in threads if th.is_alive()]
        assert not alive, f"clients hung: {len(alive)} (errors: {errors})"
    finally:
        cluster.shutdown()
    assert not errors, errors
    return results


# -- batched == solo bit-identity + the sharing counters --------------------

def test_batched_bit_identical_to_solo(table_path, monkeypatch):
    """Concurrent distinct queries batch into ONE shared-scan launch
    (SYNC_COMPILE pins the deterministic one-launch path) and every
    member's result is BIT-identical to its solo run on the same backend;
    the counters prove one upload and one launch served N stages."""
    from ballista_tpu.ops import sharedscan

    monkeypatch.setattr(sharedscan, "SYNC_COMPILE", True)
    solo = _run_sequential(table_path, QUERIES)
    shared_scan_stats(reset=True)
    routing_stats(reset=True)
    batched = _run_concurrent(table_path, QUERIES)
    stats = shared_scan_stats(reset=True)
    routing = routing_stats(reset=True)
    for q, got, want in zip(QUERIES, batched, solo):
        assert got == want, (q, got, want)
    assert stats.get("batches_formed", 0) >= 1, stats
    assert stats.get("batched_stages", 0) >= 2, stats
    assert stats.get("shared_groups", 0) >= 1, stats
    assert stats.get("uploads_saved", 0) >= 1, stats
    assert stats.get("launches_saved", 0) >= 1, stats
    # spliced members are visible routing decisions, not silent shortcuts
    assert routing["engines"].get("batch", 0) >= 2, routing


def test_cold_composition_falls_back_to_member_launches(table_path):
    """A composition whose combined program is not compiled yet must NOT
    stall the wave behind a multi-second trace: the members run their own
    jitted steps over the SHARED upload (uploads still saved, results
    bit-identical) while the one-launch program warms in the background."""
    solo = _run_sequential(table_path, QUERIES)
    shared_scan_stats(reset=True)
    batched = _run_concurrent(table_path, QUERIES)
    stats = shared_scan_stats(reset=True)
    for q, got, want in zip(QUERIES, batched, solo):
        assert got == want, (q, got, want)
    assert stats.get("batches_formed", 0) >= 1, stats
    assert stats.get("uploads_saved", 0) >= 1, stats
    # cold compositions took the per-member fallback (or finished warming
    # mid-run and switched — either way the wave never traced inline)
    assert (
        stats.get("warm_fallback_launches", 0) >= 1
        or stats.get("launches_saved", 0) >= 1
    ), stats


def test_shared_scan_off_forms_no_batches(table_path):
    cfg = BallistaConfig({"ballista.shared_scan": "false"})
    shared_scan_stats(reset=True)
    out = _run_concurrent(
        table_path, QUERIES,
        client_settings=_client_settings(**{"ballista.shared_scan": "false"}),
        cluster_config=cfg,
    )
    assert all(o is not None for o in out)
    assert shared_scan_stats(reset=True) == {}


def test_resident_members_degrade_to_solo(table_path):
    """With device residency ON and the member stages already warm, a
    batched dispatch degrades every member to its resident solo run —
    re-scanning what HBM already holds would undo the residency tier —
    and results stay bit-identical."""
    resident = _client_settings(**{"ballista.tpu.device_cache": "true"})
    solo = _run_sequential(table_path, QUERIES, client_settings=resident)
    shared_scan_stats(reset=True)
    out = _run_concurrent(table_path, QUERIES, client_settings=resident)
    stats = shared_scan_stats(reset=True)
    for q, got, want in zip(QUERIES, out, solo):
        assert got == want, (q, got, want)
    # the scheduler may form batches (it cannot see executor residency);
    # the executor's precompute hands every warm member back
    assert stats.get("shared_groups", 0) == 0, stats
    assert stats.get("uploads_saved", 0) == 0, stats


# -- evidence gate ----------------------------------------------------------

def test_evidence_gate_declines_predicted_slow_batches(table_path):
    """With warm solo task.run rates and a stage.batch rate that predicts
    the batch SLOWER than the members' solo sum, formation dispatches solo
    — recorded (batch_gate_solo + a routing decision), never silent — and
    results are unchanged. Re-seeding the batch rate fast re-enables
    batching: the gate is evidence, not a switch."""
    # warm the scheduler-observed task.run rates past MIN_OBSERVATIONS:
    # 4 sequential runs of each shape = 4 completions per stage-1 op (the
    # in-memory cost store is process-global and pinned to dir "")
    _run_sequential(table_path, QUERIES * 4)
    assert any(
        k.startswith("task.run|") for k in costmodel.snapshot()
    ), "warm pass recorded no task.run rates"
    for k in (2.0, 4.0, 8.0):
        costmodel.seed("stage.batch", k, 1e6, engine="task")
    solo = _run_sequential(table_path, QUERIES)
    shared_scan_stats(reset=True)
    gated = _run_concurrent(table_path, QUERIES)
    stats = shared_scan_stats(reset=True)
    for q, got, want in zip(QUERIES, gated, solo):
        assert got == want, (q, got, want)
    assert stats.get("batches_formed", 0) == 0, stats
    assert stats.get("batch_gate_solo", 0) >= 1, stats
    # favorable evidence: batching resumes
    for k in (2.0, 4.0, 8.0):
        costmodel.seed("stage.batch", k, 1e-6, engine="task")
    shared_scan_stats(reset=True)
    fast = _run_concurrent(table_path, QUERIES)
    stats = shared_scan_stats(reset=True)
    for q, got, want in zip(QUERIES, fast, solo):
        assert got == want, (q, got, want)
    assert stats.get("batches_formed", 0) >= 1, stats


# -- mixed compatible/incompatible groups -----------------------------------

def test_mixed_compatibility_batches_only_compatible_members(table_path):
    """A member whose stage reads a string device column cannot share the
    upload (per-stage dictionaries); it degrades to solo while the
    compatible members still batch — and everyone's result is exactly its
    solo result."""
    queries = QUERIES + [STRING_FILTER_QUERY]
    solo = _run_sequential(table_path, queries)
    shared_scan_stats(reset=True)
    batched = _run_concurrent(table_path, queries)
    stats = shared_scan_stats(reset=True)
    for q, got, want in zip(queries, batched, solo):
        assert got == want, (q, got, want)
    assert stats.get("batches_formed", 0) >= 1, stats
    assert stats.get("member_ineligible", 0) >= 1, stats


# -- scheduler.batch chaos --------------------------------------------------

def test_chaos_torn_batch_formation_degrades_to_solo(table_path):
    """scheduler.batch chaos at rate 1.0 tears EVERY formation before any
    sibling's Running flip: everything dispatches solo (nothing written,
    nothing torn) and results stay bit-identical."""
    solo = _run_sequential(table_path, QUERIES)
    chaos_cfg = BallistaConfig({
        "ballista.chaos.rate": "1.0",
        "ballista.chaos.seed": "7",
        "ballista.chaos.sites": "scheduler.batch",
    })
    shared_scan_stats(reset=True)
    recovery_stats(reset=True)
    out = _run_concurrent(table_path, QUERIES, cluster_config=chaos_cfg)
    stats = shared_scan_stats(reset=True)
    rec = recovery_stats(reset=True)
    for q, got, want in zip(QUERIES, out, solo):
        assert got == want, (q, got, want)
    assert stats.get("batches_formed", 0) == 0, stats
    assert stats.get("batch_chaos_solo", 0) >= 1, stats
    assert rec.get("chaos_injected", 0) >= 1, rec


# -- member failure isolation -----------------------------------------------

def test_member_failure_spares_batch_siblings(table_path):
    """One member's task.execute chaos (attempt 0 faulted, attempt 1 clean,
    armed via that job's OWN settings) fails the member alone: its retry
    completes and every batch sibling's result is bit-identical to solo."""
    # find a seed that faults exactly the batchable stage-1 task's first
    # attempt and nothing else the faulted job runs (stage 2 has
    # shuffle.partitions=2 tasks)
    seed = None
    for cand in range(500):
        inj = ChaosInjector(cand, 0.25, sites=("task.execute",))
        if (
            inj.should_inject("task.execute", "1/0@a0")
            and not inj.should_inject("task.execute", "1/0@a1")
            and not any(
                inj.should_inject("task.execute", f"2/{p}@a0")
                for p in range(2)
            )
        ):
            seed = cand
            break
    assert seed is not None
    solo = _run_sequential(table_path, QUERIES)
    per_query = [None] * len(QUERIES)
    per_query[1] = {
        "ballista.chaos.rate": "0.25",
        "ballista.chaos.seed": str(seed),
        "ballista.chaos.sites": "task.execute",
    }
    shared_scan_stats(reset=True)
    recovery_stats(reset=True)
    out = _run_concurrent(
        table_path, QUERIES, per_query_settings=per_query,
    )
    stats = shared_scan_stats(reset=True)
    rec = recovery_stats(reset=True)
    for q, got, want in zip(QUERIES, out, solo):
        assert got == want, (q, got, want)
    assert rec.get("task_retry", 0) >= 1, rec
    assert stats.get("batches_formed", 0) >= 1, stats


def test_executor_death_mid_batch_recovers_bit_identical(table_path):
    """The executor dies WHILE a shared-scan batch runs on it (one member
    slowed by task.slow keeps the batch in flight): every member's task
    requeues through the normal lease machinery onto the replacement
    executor and completes bit-identical to solo — a batched dispatch is N
    ordinary in-flight tasks to every recovery path."""
    import ballista_tpu.scheduler.state as state_mod

    solo = _run_sequential(table_path, QUERIES)
    per_query = [None] * len(QUERIES)
    per_query[0] = {
        # rate 1.0: EVERY attempt of this job's tasks sleeps, keeping the
        # batch mid-flight when the victim dies (retries sleep too — the
        # join timeout absorbs them)
        "ballista.chaos.rate": "1.0",
        "ballista.chaos.seed": "3",
        "ballista.chaos.sites": "task.slow",
        "ballista.chaos.slow_ms": "2500",
    }

    old_lease = state_mod.EXECUTOR_LEASE_SECS
    state_mod.EXECUTOR_LEASE_SECS = 1.0

    def kill_victim(cluster):
        cluster.scheduler_impl.lost_task_check_interval = 0.5
        time.sleep(0.8)  # the batch is dispatched and sleeping in a member
        victim = cluster.executors[0]
        victim.poll_loop.stop()
        victim.flight.shutdown()
        time.sleep(1.5)  # lease expiry
        ex = BallistaExecutor(
            "127.0.0.1", cluster.port,
            config=cluster.config, executor_id="survivor",
        )
        ex.start()
        cluster.executors.append(ex)

    shared_scan_stats(reset=True)
    recovery_stats(reset=True)
    try:
        out = _run_concurrent(
            table_path, QUERIES, per_query_settings=per_query,
            mid_flight=kill_victim, join_timeout=180,
        )
    finally:
        state_mod.EXECUTOR_LEASE_SECS = old_lease
    stats = shared_scan_stats(reset=True)
    rec = recovery_stats(reset=True)
    for q, got, want in zip(QUERIES, out, solo):
        assert got == want, (q, got, want)
    assert stats.get("batches_formed", 0) >= 1, stats
    assert rec.get("lost_task_reset", 0) >= 1, rec


# -- fuzz slice: concurrent distinct queries over shared tables -------------

_FUZZ_AGGS = [
    "sum(v)", "count(*)", "min(q)", "max(q)", "sum(q)", "min(d)", "max(d)",
    "avg(v)",
]
_FUZZ_PREDS = ["v > 0", "q < 25", "d >= date '1995-01-01'", "v < 50 and q > 5"]


def _fuzz_queries(qrng, k=3):
    out = []
    for _ in range(k):
        key = str(qrng.choice(["g", "s", "g, s"]))
        picks = list(qrng.choice(
            _FUZZ_AGGS, size=int(qrng.integers(1, 4)), replace=False
        ))
        sel = ", ".join([key] + [f"{a} as a{i}" for i, a in enumerate(picks)])
        sql = f"select {sel} from t"
        if qrng.random() < 0.6:
            sql += " where " + str(qrng.choice(_FUZZ_PREDS))
        out.append(sql + f" group by {key} order by {key}")
    return out


@pytest.mark.parametrize("seed", range(2))
def test_fuzz_concurrent_shared_scan(tmp_path, seed):
    """Fuzz slice (ISSUE 13): random concurrent DISTINCT aggregate queries
    over one shared table, batched dispatch ON, compared bit-exactly
    against the sequential (never-batched) run of the same cluster shape.
    Own rng streams (22000+ data, 23000+ queries), so every baseline
    stream in test_fuzz_device.py stays byte-identical."""
    rng = np.random.default_rng(22000 + seed)
    qrng = np.random.default_rng(23000 + seed)
    n = int(rng.integers(5_000, 30_000))
    t = pa.table({
        "g": pa.array([f"k{v}" for v in rng.integers(0, 8, n)]),
        "s": pa.array([f"x{v}" for v in rng.integers(0, 3, n)]),
        "v": pa.array(np.round(rng.uniform(-1000, 1000, n), 2)),
        "q": pa.array(rng.integers(1, 100, n), type=pa.int64()),
        "d": pa.array(
            rng.integers(8000, 12000, n), type=pa.int32()
        ).cast(pa.date32()),
    })
    path = str(tmp_path / "t.parquet")
    pq.write_table(t, path)
    queries = _fuzz_queries(qrng)
    solo = _run_sequential(path, queries)
    batched = _run_concurrent(path, queries)
    for q, got, want in zip(queries, batched, solo):
        assert got == want, (q, got, want)


# -- weighted fair-share sibling ordering (ISSUE 14 satellite) ---------------

def test_form_shared_batch_fair_share_sibling_order():
    """PR 13 residue: sibling selection must honor the same smallest
    in_flight/weight fair-share key assignment uses — a heavy tenant with
    many co-pending compatible stages can no longer fill every sibling
    slot of a batch while a lighter tenant has compatible work. Pre-fix,
    candidates were visited in KV insertion order, so the heavy tenant's
    (earlier-submitted) jobs consumed all max_batch-1 slots."""
    from ballista_tpu.proto import ballista_pb2 as pb
    from ballista_tpu.scheduler.kv import MemoryBackend
    from ballista_tpu.scheduler.state import SchedulerState

    state = SchedulerState(
        MemoryBackend(), "fairshare",
        config=BallistaConfig({
            "ballista.shared_scan.max_batch": "4",  # 3 sibling slots
            "ballista.tpu.cost_model_dir": "",
        }),
    )

    def add_job(job_id, tenant):
        running = pb.JobStatus()
        running.running.SetInParent()
        state.save_job_metadata(job_id, running)
        state.save_job_tenant(job_id, tenant, 0)
        st = pb.TaskStatus()
        st.partition_id.job_id = job_id
        st.partition_id.stage_id = 1
        st.partition_id.partition_id = 0
        state.save_task_status(st)

    # the heavy tenant submits FIRST (insertion order favored it pre-fix)
    # and already has 4 running tasks in flight; the light tenant has none
    for j in ("h1", "h2", "h3", "h4"):
        add_job(j, "heavy")
    add_job("l1", "light")
    for i in range(4):
        run = pb.TaskStatus()
        run.partition_id.job_id = "h-running"
        run.partition_id.stage_id = 9
        run.partition_id.partition_id = i
        run.running.executor_id = "e-other"
        state.save_task_status(run)
    state.save_job_tenant("h-running", "heavy", 0)
    rj = pb.JobStatus()
    rj.running.SetInParent()
    state.save_job_metadata("h-running", rj)

    # primary already assigned (another heavy job)
    primary = pb.TaskStatus()
    primary.partition_id.job_id = "h0"
    primary.partition_id.stage_id = 1
    primary.partition_id.partition_id = 0
    primary.running.executor_id = "e1"
    state.save_job_tenant("h0", "heavy", 0)
    pj = pb.JobStatus()
    pj.running.SetInParent()
    state.save_job_metadata("h0", pj)

    # unit harness: every candidate stage is scan-compatible and binds
    sig = ("ParquetScanExec", ("f.parquet",), False, 1)
    state._cached_stage_signature = lambda j, s: sig
    state._bound_stage_plan = lambda j, s, idx: object()

    out = state.form_shared_batch(primary, object(), "e1")
    members = [st.partition_id.job_id for st, _plan in out]
    assert len(members) == 3
    # the light tenant's job MUST hold a slot (pre-fix: ['h1','h2','h3'])
    assert "l1" in members, members
    # and the re-ranking interleaves rather than draining one tenant:
    # light (0 in flight) first, then heavy's fair share
    assert members[0] == "l1", members


# -- layout-warm members are shared-scan-eligible (ISSUE 15 satellite) -------

def test_layout_warm_member_batches_bit_identical(table_path, tmp_path):
    """PR 13 residue: batch.size now folds into the stage/persist key, so a
    persisted-layout-WARM member is shared-scan-eligible — the warm layout
    is guaranteed to be at this dispatch's batch granularity, making the
    shared batch stream row-identical to the member's layout-cache solo
    run. Pre-fix, any member with a persist key and a configured layout dir
    silently degraded to solo. Warm the persisted layouts with a sequential
    pass, then batch concurrently on the SAME layout dir: batches must
    form and every member must be bit-identical to its warm solo run."""
    layout_dir = str(tmp_path / "layouts")
    warm = _client_settings(
        **{"ballista.tpu.layout_cache_dir": layout_dir}
    )
    # sequential warm pass: persists each member stage's layout
    solo = _run_sequential(table_path, QUERIES, client_settings=warm)
    import os

    assert os.path.isdir(layout_dir) and os.listdir(layout_dir), (
        "warm pass persisted no layout entries — the regression test "
        "would not exercise the layout-warm path"
    )
    shared_scan_stats(reset=True)
    batched = _run_concurrent(table_path, QUERIES, client_settings=warm)
    stats = shared_scan_stats(reset=True)
    for q, got, want in zip(QUERIES, batched, solo):
        assert got == want, (q, got, want)
    # the whole point: layout-warm members now group and share the scan
    assert stats.get("batches_formed", 0) >= 1, stats
    assert stats.get("shared_groups", 0) >= 1, stats
    assert stats.get("uploads_saved", 0) >= 1, stats

"""High-cardinality device aggregation: the sorted chunked-segment layout
(ops/layout.py) replaces the round-1 MAX_GROUPS=1024 decline-to-host."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from ballista_tpu.config import BallistaConfig
from ballista_tpu.engine import ExecutionContext
from ballista_tpu.logical import col, functions as F, lit


def _write(tmp_path, table, name="t.parquet"):
    p = tmp_path / name
    pq.write_table(table, str(p))
    return str(p)


def _ctx(backend):
    return ExecutionContext(BallistaConfig({"ballista.executor.backend": backend}))


def _make_table(n=200_000, g=5000, seed=0):
    rng = np.random.default_rng(seed)
    return pa.table(
        {
            "k": pa.array(rng.integers(0, g, n), type=pa.int64()),
            "v": pa.array(rng.uniform(-100, 100, n).astype(np.float64)),
            "w": pa.array(rng.integers(-1000, 1000, n), type=pa.int64()),
            "f": pa.array(rng.uniform(0, 1, n).astype(np.float64)),
        }
    )


def test_highcard_groupby_matches_host_and_oracle(tmp_path):
    table = _make_table()
    path = _write(tmp_path, table)

    results = {}
    for backend in ("tpu", "host"):
        ctx = _ctx(backend)
        ctx.register_parquet("t", path)
        df = (
            ctx.table("t")
            .filter(col("f") > lit(0.25))
            .aggregate(
                [col("k")],
                [
                    F.sum(col("v")).alias("sv"),
                    F.count(col("v")).alias("c"),
                    F.min(col("v")).alias("mn"),
                    F.max(col("v")).alias("mx"),
                    F.avg(col("v")).alias("av"),
                    F.sum(col("w")).alias("sw"),
                ],
            )
            .sort(col("k").sort())
        )
        results[backend] = df.collect()

    t, h = results["tpu"], results["host"]
    assert t.column("k").to_pylist() == h.column("k").to_pylist()
    assert t.column("c").to_pylist() == h.column("c").to_pylist()
    # integer sums are exact on the device path
    assert t.column("sw").to_pylist() == h.column("sw").to_pylist()
    # float sums carry the documented f32 accumulation tolerance: absolute
    # error ~ eps * sum(|v|) per group, which dominates rtol when values
    # cancel (sums near zero)
    # min/max carry f32 narrowing of the f64 source column (rel ~ 6e-8)
    for name, rtol, atol in (("sv", 1e-4, 2e-3), ("mn", 1e-6, 1e-5),
                             ("mx", 1e-6, 1e-5), ("av", 1e-4, 1e-4)):
        np.testing.assert_allclose(
            t.column(name).to_numpy(), h.column(name).to_numpy(), rtol=rtol,
            atol=atol, err_msg=name,
        )

    # independent pyarrow oracle on one aggregate
    mask = np.asarray(table.column("f")) > 0.25
    oracle = (
        table.filter(pa.array(mask))
        .group_by("k")
        .aggregate([("v", "sum")])
        .sort_by("k")
    )
    np.testing.assert_allclose(
        t.column("sv").to_numpy(), oracle.column("v_sum").to_numpy(),
        rtol=1e-4, atol=2e-3,
    )


def test_highcard_uses_sorted_layout(tmp_path):
    """Belt-and-braces: the query above must actually run the sorted device
    path, not silently fall back to host."""
    from ballista_tpu.ops import kernels

    table = _make_table(n=50_000, g=3000)
    path = _write(tmp_path, table)
    kernels._stage_cache.clear()
    kernels._stage_cache_pins.clear()
    ctx = _ctx("tpu")
    ctx.register_parquet("t", path)
    out = (
        ctx.table("t")
        .aggregate([col("k")], [F.sum(col("v")).alias("s")])
        .collect()
    )
    assert out.num_rows == 3000
    stages = [s for s in kernels._stage_cache.values() if s not in (False, None)]
    assert stages, "device stage was not engaged"
    kinds = {
        ent.get("kind")
        for s in stages
        for ent in s._device_cache.values()
    }
    assert "sorted" in kinds


def test_pallas_sorted_kernel_path(tmp_path):
    """ballista.tpu.sorted_kernel=pallas routes high-cardinality
    sum/count/avg through the MXU one-hot kernel and matches the host."""
    from ballista_tpu.ops import kernels

    table = _make_table(n=60_000, g=2000)
    path = _write(tmp_path, table)
    kernels._stage_cache.clear()
    ctx = ExecutionContext(
        BallistaConfig({"ballista.executor.backend": "tpu",
                        "ballista.tpu.sorted_kernel": "pallas"})
    )
    ctx.register_parquet("t", path)
    t = (
        ctx.table("t")
        .filter(col("f") > lit(0.4))
        .aggregate([col("k")], [F.sum(col("v")).alias("s"),
                                F.count(col("v")).alias("c"),
                                F.avg(col("v")).alias("a")])
        .sort(col("k").sort())
        .collect()
    )
    hctx = ExecutionContext(BallistaConfig({"ballista.executor.backend": "host"}))
    hctx.register_parquet("t", path)
    h = (
        hctx.table("t")
        .filter(col("f") > lit(0.4))
        .aggregate([col("k")], [F.sum(col("v")).alias("s"),
                                F.count(col("v")).alias("c"),
                                F.avg(col("v")).alias("a")])
        .sort(col("k").sort())
        .collect()
    )
    assert t.column("c").to_pylist() == h.column("c").to_pylist()
    np.testing.assert_allclose(t.column("s").to_numpy(), h.column("s").to_numpy(),
                               rtol=1e-4, atol=2e-3)
    np.testing.assert_allclose(t.column("a").to_numpy(), h.column("a").to_numpy(),
                               rtol=1e-4, atol=1e-4)
    stages = [s for s in kernels._stage_cache.values() if s not in (False, None)]
    kinds = {e.get("kind") for s in stages for e in s._device_cache.values()}
    assert "pallas_sorted" in kinds


def test_skewed_groups_multi_chunk_fold(tmp_path):
    """One giant group among many small ones exercises the chunk fold
    (owner reduceat) path, min/max included."""
    rng = np.random.default_rng(1)
    k = np.concatenate([np.zeros(120_000, np.int64),
                        rng.integers(1, 2000, 30_000)])
    v = rng.uniform(-50, 50, len(k))
    table = pa.table({"k": k, "v": v})
    path = _write(tmp_path, table)

    outs = {}
    for backend in ("tpu", "host"):
        ctx = _ctx(backend)
        ctx.register_parquet("t", path)
        outs[backend] = (
            ctx.table("t")
            .aggregate([col("k")], [F.sum(col("v")).alias("s"),
                                    F.min(col("v")).alias("mn"),
                                    F.max(col("v")).alias("mx"),
                                    F.count(col("v")).alias("c")])
            .sort(col("k").sort())
            .collect()
        )
    t, h = outs["tpu"], outs["host"]
    assert t.column("c").to_pylist() == h.column("c").to_pylist()
    np.testing.assert_allclose(t.column("s").to_numpy(), h.column("s").to_numpy(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(t.column("mn").to_numpy(), h.column("mn").to_numpy())
    np.testing.assert_allclose(t.column("mx").to_numpy(), h.column("mx").to_numpy())


def test_int_sum_exactness_small_g(tmp_path):
    """Integer sums on the unrolled (small-G) path are exact even where f32
    would round (values above 2^24)."""
    rng = np.random.default_rng(2)
    n = 50_000
    table = pa.table(
        {
            "k": pa.array(rng.integers(0, 4, n), type=pa.int64()),
            "v": pa.array(rng.integers(16_000_000, 17_000_000, n), type=pa.int64()),
        }
    )
    path = _write(tmp_path, table)
    outs = {}
    for backend in ("tpu", "host"):
        ctx = _ctx(backend)
        ctx.register_parquet("t", path)
        outs[backend] = (
            ctx.table("t")
            .aggregate([col("k")], [F.sum(col("v")).alias("s")])
            .sort(col("k").sort())
            .collect()
        )
    # int32 would overflow on these sums -> device declines, host path runs,
    # results still exact
    assert outs["tpu"].column("s").to_pylist() == outs["host"].column("s").to_pylist()


def test_int_sum_exact_on_device(tmp_path):
    """In-range integer sums accumulate in int32 on device and come back
    exact (the ADVICE r1 f32-rounding case)."""
    rng = np.random.default_rng(3)
    n = 60_000
    k = rng.integers(0, 8, n)
    v = rng.integers(250, 300, n)  # per-group sums ~2.1e6 > 2^24 / 8
    table = pa.table({"k": pa.array(k, type=pa.int64()),
                      "v": pa.array(v, type=pa.int64())})
    path = _write(tmp_path, table)
    ctx = _ctx("tpu")
    ctx.register_parquet("t", path)
    out = (
        ctx.table("t")
        .aggregate([col("k")], [F.sum(col("v")).alias("s")])
        .sort(col("k").sort())
        .collect()
    )
    oracle = {}
    for kk, vv in zip(k, v):
        oracle[kk] = oracle.get(kk, 0) + int(vv)
    assert out.column("s").to_pylist() == [oracle[i] for i in sorted(oracle)]

"""Tests for the surrounding tooling: DB-API, TPC-H CLI helpers, config
precedence, diagrams, tracing."""

import json
import os
import subprocess
import sys
import time

import pyarrow as pa
import pytest


def test_dbapi_local(sales_table):
    import ballista_tpu.client.dbapi as db

    conn = db.connect(local=True)
    conn.context.register_record_batches("sales", sales_table)
    cur = conn.cursor()
    cur.execute("select region, sum(amount) as s from sales group by region order by s")
    assert cur.description[0][0] == "region"
    rows = cur.fetchall()
    assert rows == [("north", 40.0), ("east", 120.0), ("west", 145.0)]
    cur.execute("select id from sales where amount > ? order by id", (40,))
    assert [r[0] for r in cur.fetchall()] == [7, 8, 9]
    assert cur.fetchone() is None or True  # exhausted
    cur.execute("select id from sales order by id limit 3")
    assert cur.fetchone() == (0,)
    assert cur.fetchmany(2) == [(1,), (2,)]
    conn.close()
    with pytest.raises(db.InterfaceError):
        conn.cursor()


def test_dbapi_error():
    import ballista_tpu.client.dbapi as db

    conn = db.connect(local=True)
    with pytest.raises(db.DatabaseError):
        conn.cursor().execute("select * from nonexistent")


def test_dbapi_type_mapping_and_metadata(sales_table):
    """PEP 249 type objects, description matrix, catalog metadata — the
    JDBC driver's FlightResultSetMetaData / DatabaseMetaData roles."""
    import ballista_tpu.client.dbapi as db

    with db.connect(local=True) as conn:
        conn.context.register_record_batches("sales", sales_table)
        assert conn.get_tables() == ["sales"]
        cols = dict((c[0], c) for c in conn.get_columns("sales"))
        assert cols["region"][1] == db.STRING
        assert cols["amount"][1] == db.NUMBER
        with pytest.raises(db.ProgrammingError):
            conn.get_columns("nope")

        with conn.cursor() as cur:
            cur.execute("select region, amount, qty from sales limit 1")
            d = {c[0]: c for c in cur.description}
            assert d["region"][1] == db.STRING and d["region"][1] != db.NUMBER
            assert d["amount"][1] == db.NUMBER
            assert d["amount"][4] == 15  # double precision digits
            assert d["qty"][3] == 4  # int32 internal size


def test_dbapi_parameter_binding(sales_table):
    """qmark binding must not touch '?' inside string literals and must
    reject arity mismatches (PreparedStatement analog)."""
    import ballista_tpu.client.dbapi as db

    conn = db.connect(local=True)
    conn.context.register_record_batches("sales", sales_table)
    cur = conn.cursor()
    cur.execute(
        "select count(*) as n from sales where region != 'what?' and amount > ?",
        (100,),
    )
    assert cur.fetchone() == (0,)
    with pytest.raises(db.ProgrammingError):
        cur.execute("select ? + 1", ())
    with pytest.raises(db.ProgrammingError):
        cur.execute("select 1", (5,))
    with pytest.raises(db.ProgrammingError):
        cur.execute("select ?", (object(),))
    # '?' inside comments and quoted identifiers must not bind
    from ballista_tpu.client.dbapi import _bind

    assert _bind("select a -- total?\nfrom t where id = ?", [7]).endswith("id = 7")
    assert "?" in _bind("select a /* what? */ from t where id = ?", [7]).split("*/")[0]
    assert _bind('select "a?b" from t where id = ?', [7]).startswith('select "a?b"')
    # Decimal parameters bind as exact decimal text
    import decimal

    assert _bind("select ?", [decimal.Decimal("10.50")]) == "select 10.50"


def test_daemon_config_precedence(tmp_path, monkeypatch):
    from ballista_tpu.daemon_config import SCHEDULER_SPEC, load_config

    # default
    cfg = load_config(SCHEDULER_SPEC, "BT_TEST_", "", argv=[])
    assert cfg["port"] == 50050
    # env beats default
    monkeypatch.setenv("BT_TEST_PORT", "60000")
    cfg = load_config(SCHEDULER_SPEC, "BT_TEST_", "", argv=[])
    assert cfg["port"] == 60000
    # file beats env
    f = tmp_path / "cfg.toml"
    f.write_text('port = 60001\nnamespace = "ns-file"\n')
    cfg = load_config(SCHEDULER_SPEC, "BT_TEST_", "", argv=["--config-file", str(f)])
    assert cfg["port"] == 60001 and cfg["namespace"] == "ns-file"
    # CLI beats file
    cfg = load_config(
        SCHEDULER_SPEC, "BT_TEST_", "", argv=["--config-file", str(f), "--port", "60002"]
    )
    assert cfg["port"] == 60002


def test_stage_diagram(sales_table):
    from ballista_tpu.distributed.planner import DistributedPlanner
    from ballista_tpu.engine import ExecutionContext
    from ballista_tpu.logical import col, functions as F
    from ballista_tpu.utils.diagram import plan_diagram, produce_diagram

    ctx = ExecutionContext()
    ctx.register_record_batches("sales", sales_table, n_partitions=2)
    df = ctx.table("sales").aggregate([col("region")], [F.sum(col("amount")).alias("s")])
    physical = ctx.create_physical_plan(df.logical_plan())
    stages = DistributedPlanner().plan_query_stages("jobx", physical)
    dot = produce_diagram(stages)
    assert dot.startswith("digraph G {") and "shuffle" in dot
    assert dot.count("subgraph cluster_") == len(stages)
    single = plan_diagram(physical)
    assert "HashAggregateExec" in single


def test_tracing_spans(sales_table):
    from ballista_tpu.engine import ExecutionContext
    from ballista_tpu.utils import tracing

    tracing.reset()
    ctx = ExecutionContext()
    ctx.register_record_batches("sales", sales_table)
    ctx.sql("select count(*) as n from sales").collect()
    paths = [p for p, _dt, _d in tracing.spans()]
    assert "plan" in paths and "execute" in paths
    assert "ms" in tracing.report(reset=True)
    assert tracing.spans() == []


def test_tpch_cli_benchmark(tmp_path):
    from benchmarks.tpch.datagen import generate

    d = tmp_path / "tpch"
    generate(str(d), sf=0.001, parts=1)
    env = dict(os.environ, PYTHONPATH=os.getcwd(), JAX_PLATFORMS="cpu",
               PALLAS_AXON_POOL_IPS="")
    out = subprocess.run(
        [sys.executable, "-m", "benchmarks.tpch.runner", "benchmark",
         "--path", str(d), "--query", "6", "--iterations", "1"],
        capture_output=True, text=True, env=env, timeout=180,
    )
    assert out.returncode == 0, out.stderr[-500:]
    result = json.loads(out.stdout)
    assert "q6" in result and result["q6"]["rows"] == 1


def test_bench_stale_capture_fallback(tmp_path, monkeypatch, capsys):
    """When the device probe budget exhausts, bench.py must emit the newest
    persisted session capture marked stale (exit 0), never a null record
    (VERDICT r3 #1; the reference harness always yields a record,
    rust/benchmarks/tpch/src/main.rs:117-183)."""
    import importlib

    bench = importlib.import_module("bench")
    monkeypatch.setattr(bench, "RESULTS_DIR", tmp_path)

    probe = {"reason": "timeout", "timeout_s": 30, "detail": "dead relay",
             "attempts": 3, "budget_s": 1200.0}

    # no captures at all -> returns without exiting (caller then exits 3)
    bench._emit_stale_capture(probe=probe)
    assert capsys.readouterr().out == ""

    old = {"metric": "m", "value": 1.0, "unit": "u", "vs_baseline": 1.0,
           "configs": [{"name": "q1"}]}
    new = {"metric": "m", "value": 2.0, "unit": "u", "vs_baseline": 2.0,
           "configs": [{"name": "q1"}, {"name": "q3"}]}
    (tmp_path / "session_a.json").write_text(json.dumps(old))
    (tmp_path / "session_broken.json").write_text("{not json")
    p_new = tmp_path / "session_b.json"
    p_new.write_text(json.dumps(new))
    now = time.time()
    os.utime(tmp_path / "session_a.json", (now - 100, now - 100))
    os.utime(tmp_path / "session_broken.json", (now + 10, now + 10))
    os.utime(p_new, (now, now))

    # newest *parseable* capture wins; broken JSON is skipped
    path, d = bench._latest_session_capture()
    assert path == p_new and d["value"] == 2.0

    # a CPU-jax capture must never stand in for device evidence
    p_cpu = tmp_path / "session_cpu.json"
    p_cpu.write_text(json.dumps({**new, "value": 9.0, "platform": "cpu"}))
    os.utime(p_cpu, (now + 20, now + 20))
    path, d = bench._latest_session_capture()
    assert path == p_new and d["value"] == 2.0

    with pytest.raises(SystemExit) as ei:
        bench._emit_stale_capture(probe=probe)
    assert ei.value.code == 0
    out = json.loads(capsys.readouterr().out)
    assert out["stale"] is True
    assert out["value"] == 2.0
    # structured probe record: reason/timeout_s/detail survive verbatim
    assert out["probe"] == probe
    assert out["configs"] == new["configs"]
    assert "captured_at" in out and "capture_file" in out


def test_bench_probe_failure_is_structured(monkeypatch):
    """A hung jax.devices() probe (the 30s timeout) must surface as a
    structured {reason: timeout, timeout_s, detail} record, not a raw
    exception string glued into the JSON."""
    import importlib
    import subprocess

    bench = importlib.import_module("bench")

    def fake_run(cmd, timeout, check, capture_output):
        raise subprocess.TimeoutExpired(cmd, timeout,
                                        stderr=b"relay hang\ntail line")

    monkeypatch.setattr(subprocess, "run", fake_run)
    err = bench._probe_device_once(timeout_s=30)
    assert err["reason"] == "timeout" and err["timeout_s"] == 30
    assert "tail line" in err["detail"]

    def fake_run_crash(cmd, timeout, check, capture_output):
        raise subprocess.CalledProcessError(1, cmd, stderr=b"no backend")

    monkeypatch.setattr(subprocess, "run", fake_run_crash)
    err = bench._probe_device_once(timeout_s=30)
    assert err["reason"] == "error" and "no backend" in err["detail"]

    # healthy probe -> None (the exit-0 main path)
    def fake_run_ok(cmd, timeout, check, capture_output):
        return subprocess.CompletedProcess(cmd, 0, stdout=b"[CpuDevice(0)]")

    monkeypatch.setattr(subprocess, "run", fake_run_ok)
    assert bench._probe_device_once(timeout_s=30) is None


def test_bench_persist_capture(tmp_path, monkeypatch):
    import importlib

    bench = importlib.import_module("bench")
    monkeypatch.setattr(bench, "RESULTS_DIR", tmp_path / "results")
    bench._persist_capture({"metric": "m", "value": 3.0})
    files = list((tmp_path / "results").glob("session_auto_*.json"))
    assert len(files) == 1
    d = json.loads(files[0].read_text())
    assert d["value"] == 3.0 and "provenance" in d
    # and the persisted file round-trips through the fallback scanner
    path, got = bench._latest_session_capture()
    assert path == files[0] and got["value"] == 3.0

"""Speculative execution (ISSUE 11): cost-model straggler detection,
duplicate attempts through the durable ledger, first-completion-wins, and
per-tenant latency SLOs.

The invariants under test mirror what made PRs 5/6 trustworthy:

- a duplicate attempt is dispatched ONLY through the speculation ledger
  (write-through KV), never by touching the primary's task status;
- first completion wins, whichever attempt it is — the losing sibling's
  report is dropped by the stale-attempt guards and never double-counts
  or clobbers published locations;
- a scheduler crash+restart mid-speculation recovers BOTH attempts (the
  primary from its running status + assignment ledger, the duplicate from
  the speculation ledger) and the owners' echoes re-adopt them;
- fault-free runs with the default thresholds launch nothing;
- results stay bit-identical to the fault-free baseline with speculation
  ON under seeded `task.slow` chaos (end-to-end acceptance here; the
  fuzz slice in test_fuzz_device.py widens the plan space).
"""

import time

import pyarrow as pa
import pytest

from ballista_tpu.config import BallistaConfig
from ballista_tpu.ops import costmodel
from ballista_tpu.ops.runtime import recovery_stats, speculation_stats
from ballista_tpu.proto import ballista_pb2 as pb
from ballista_tpu.scheduler.kv import MemoryBackend, SqliteBackend
from ballista_tpu.scheduler.state import SchedulerState
from ballista_tpu.utils.chaos import ChaosInjector

# -- helpers ----------------------------------------------------------------


def _spec_config(**over):
    """Speculation armed with a zero floor + 2x slack so unit tests control
    the trigger purely through the aged watch entry; cost store in-memory
    (dir ""), never touching the repo's on-disk cache."""
    base = {
        "ballista.tpu.cost_model_dir": "",
        "ballista.speculation.min_runtime_ms": "0",
        "ballista.speculation.multiplier": "2",
    }
    base.update(over)
    return BallistaConfig(base)


def _meta(i):
    return pb.ExecutorMetadata(id=i, host="h", port=1)


def _running_job(s, job="j"):
    running = pb.JobStatus()
    running.running.SetInParent()
    s.save_job_metadata(job, running)


def _pending(job, stage, part, attempt=0):
    t = pb.TaskStatus()
    t.partition_id.job_id = job
    t.partition_id.stage_id = stage
    t.partition_id.partition_id = part
    t.attempt = attempt
    return t


def _stage_plan(s, job="j", stage=1):
    from ballista_tpu.physical.basic import EmptyExec

    s.save_stage_plan(job, stage, EmptyExec(True, pa.schema([("a", pa.int64())])))


def _echo(job, stage, part, attempt):
    e = pb.RunningTaskEcho()
    e.partition_id.job_id = job
    e.partition_id.stage_id = stage
    e.partition_id.partition_id = part
    e.attempt = attempt
    return e


def _completed(job, stage, part, attempt, executor, speculative=False):
    t = _pending(job, stage, part, attempt)
    t.speculative = speculative
    t.completed.executor_id = executor
    t.completed.path = f"/w/{executor}"
    return t


def _straggling_state(kv=None, config=None):
    """A state with one RUNNING task on e1 (aged 5s into its watch entry),
    a second live executor e2, and a warm task.run prediction of ~1ms —
    grossly exceeded, so the straggler monitor fires on the next idle
    slot."""
    costmodel.reset()
    s = SchedulerState(kv or MemoryBackend(), "t", config=config or _spec_config())
    _running_job(s)
    s.save_executor_metadata(_meta("e1"))
    s.save_executor_metadata(_meta("e2"))
    _stage_plan(s)
    s.save_task_status(_pending("j", 1, 0))
    assert s.assign_next_schedulable_task("e1") is not None
    costmodel.seed(s._task_run_op("j", 1), 1.0, 0.001, engine="task")
    owner, attempt, t0 = s._running_since[("j", 1, 0)]
    s._running_since[("j", 1, 0)] = (owner, attempt, t0 - 5.0)
    return s


SPEC_KEY = "/ballista/t/speculation/j/1/0"


# -- straggler detection + duplicate dispatch -------------------------------


def test_straggler_launches_duplicate_through_the_ledger():
    speculation_stats(reset=True)
    s = _straggling_state()
    got = s.maybe_speculate("e2")
    assert got is not None
    dup, plan = got
    assert dup.attempt == 1 and dup.speculative
    assert plan is not None
    # write-through ledger record: the restart truth for the duplicate
    raw = s.kv.get(SPEC_KEY)
    assert raw is not None
    a = pb.Assignment()
    a.ParseFromString(raw)
    assert a.executor_id == "e2" and a.attempt == 1
    # the PRIMARY's task status is untouched: still running attempt 0 on e1
    cur = s.get_task_status("j", 1, 0)
    assert cur.WhichOneof("status") == "running"
    assert cur.attempt == 0 and cur.running.executor_id == "e1"
    assert speculation_stats().get("launched") == 1
    # never twice on one task; never back onto the primary's owner
    assert s.maybe_speculate("e2") is None
    assert s.maybe_speculate("e1") is None


def test_cold_model_never_speculates():
    """No prediction -> no speculation: a cold store reproduces
    pre-speculation scheduling exactly."""
    costmodel.reset()
    s = SchedulerState(MemoryBackend(), "t", config=_spec_config())
    _running_job(s)
    s.save_executor_metadata(_meta("e1"))
    s.save_executor_metadata(_meta("e2"))
    _stage_plan(s)
    s.save_task_status(_pending("j", 1, 0))
    assert s.assign_next_schedulable_task("e1") is not None
    owner, attempt, t0 = s._running_since[("j", 1, 0)]
    s._running_since[("j", 1, 0)] = (owner, attempt, t0 - 300.0)
    assert s.maybe_speculate("e2") is None


def test_default_floor_spares_fresh_tasks():
    """Fault-free runs with default thresholds launch nothing: a task
    younger than ballista.speculation.min_runtime_ms never speculates,
    whatever the model predicts."""
    speculation_stats(reset=True)
    s = _straggling_state(
        config=_spec_config(**{"ballista.speculation.min_runtime_ms": "500000"})
    )
    assert s.maybe_speculate("e2") is None
    assert speculation_stats().get("launched", 0) == 0


def test_speculation_disabled_by_config():
    s = _straggling_state(
        config=_spec_config(**{"ballista.speculation": "false"})
    )
    assert s.maybe_speculate("e2") is None


def test_executor_that_failed_an_attempt_is_not_trusted():
    """The tail-latency rescue must not land on an executor that already
    failed an attempt of this task."""
    s = _straggling_state()
    cur = s.get_task_status("j", 1, 0)
    h = cur.history.add()
    h.attempt = 0
    h.executor_id = "e2"
    h.error = "boom"
    s.save_task_status(cur)
    owner, attempt, t0 = s._running_since[("j", 1, 0)]
    s._running_since[("j", 1, 0)] = (owner, attempt, t0 - 5.0)
    assert s.maybe_speculate("e2") is None


# -- first completion wins --------------------------------------------------


def test_duplicate_wins_primary_report_dropped():
    speculation_stats(reset=True)
    s = _straggling_state()
    assert s.maybe_speculate("e2") is not None
    # the duplicate (attempt 1) completes first
    assert s.accept_task_status(_completed("j", 1, 0, 1, "e2", speculative=True))
    assert s.kv.get(SPEC_KEY) is None
    stats = speculation_stats()
    assert stats.get("won") == 1
    assert stats.get("wasted_seconds", 0) > 0
    # the straggling primary finally reports: dropped as stale, and the
    # winner's published location stands
    recovery_stats(reset=True)
    assert not s.accept_task_status(_completed("j", 1, 0, 0, "e1"))
    assert recovery_stats().get("stale_status_dropped") == 1
    cur = s.get_task_status("j", 1, 0)
    assert cur.WhichOneof("status") == "completed"
    assert cur.attempt == 1 and cur.completed.executor_id == "e2"


def test_primary_wins_duplicate_report_dropped():
    """The numeric attempt guard alone would let the higher-numbered
    duplicate clobber the primary's completion — the completion-stands
    guard must drop it."""
    speculation_stats(reset=True)
    s = _straggling_state()
    assert s.maybe_speculate("e2") is not None
    assert s.accept_task_status(_completed("j", 1, 0, 0, "e1"))
    stats = speculation_stats()
    assert stats.get("lost") == 1
    assert s.kv.get(SPEC_KEY) is None
    recovery_stats(reset=True)
    assert not s.accept_task_status(_completed("j", 1, 0, 1, "e2", speculative=True))
    assert recovery_stats().get("stale_status_dropped") == 1
    cur = s.get_task_status("j", 1, 0)
    assert cur.attempt == 0 and cur.completed.executor_id == "e1"


def test_failed_duplicate_spares_the_primary():
    """A dying duplicate retires the speculation without consuming the
    task's retry budget or touching the primary."""
    speculation_stats(reset=True)
    s = _straggling_state()
    assert s.maybe_speculate("e2") is not None
    failed = _pending("j", 1, 0, attempt=1)
    failed.speculative = True
    failed.failed.error = "dup died"
    assert not s.accept_task_status(failed)
    assert speculation_stats().get("failed") == 1
    assert s.kv.get(SPEC_KEY) is None
    cur = s.get_task_status("j", 1, 0)
    assert cur.WhichOneof("status") == "running" and cur.attempt == 0
    # the primary then completes normally
    assert s.accept_task_status(_completed("j", 1, 0, 0, "e1"))


def test_duplicate_fetch_failure_still_recomputes_the_lost_map():
    """Review regression: a duplicate's fetch_failed report is dropped (the
    primary still runs, no retry budget consumed) — but the lineage it
    carries must NOT be: the named lost map output is recomputed now, not
    after the next consumer trips on it a failure round-trip later."""
    speculation_stats(reset=True)
    s = _straggling_state()
    # a completed upstream map output the duplicate will report lost
    map_done = _completed("j", 0, 0, 0, "em")
    s.save_task_status(map_done)
    assert s.maybe_speculate("e2") is not None
    recovery_stats(reset=True)
    ff = _pending("j", 1, 0, attempt=1)
    ff.speculative = True
    ff.fetch_failed.executor_id = "e2"
    ff.fetch_failed.error = "connection refused"
    ff.fetch_failed.map_stage_id = 0
    ff.fetch_failed.map_partition_id = 0
    ff.fetch_failed.map_executor_id = "em"
    ff.fetch_failed.path = "/w/em"
    assert not s.accept_task_status(ff)
    assert speculation_stats().get("failed") == 1
    assert s.kv.get(SPEC_KEY) is None
    # the lost map output was requeued for recompute with the lineage
    assert recovery_stats().get("map_recomputed") == 1
    mt = s.get_task_status("j", 0, 0)
    assert mt.WhichOneof("status") is None and mt.attempt == 1
    assert mt.history[0].executor_id == "em"
    # the primary is untouched
    cur = s.get_task_status("j", 1, 0)
    assert cur.WhichOneof("status") == "running" and cur.attempt == 0


def test_saturated_tenant_gets_no_speculative_slot():
    """Review regression: the rescue must not grant a tenant past its
    max_inflight quota an extra physical slot — the PR 7 starvation bound
    holds for duplicates too."""
    s = _straggling_state(
        config=_spec_config(**{"ballista.tenant.max_inflight": "1"})
    )
    s.save_job_tenant("j", "alice", 0)
    assert s.maybe_speculate("e2") is None  # alice saturated at 1 in flight
    s2 = _straggling_state(
        config=_spec_config(**{"ballista.tenant.max_inflight": "2"})
    )
    s2.save_job_tenant("j", "alice", 0)
    assert s2.maybe_speculate("e2") is not None  # headroom: rescue allowed


def test_primary_failure_promotes_the_duplicate():
    """The primary dies while its duplicate is in flight: the duplicate IS
    the retry — promoted to the current attempt on its executor, entering
    the normal assignment ledger, consuming no retry budget."""
    speculation_stats(reset=True)
    s = _straggling_state()
    assert s.maybe_speculate("e2") is not None
    spec_t0 = s._speculative[("j", 1, 0)][2]
    t = s.get_task_status("j", 1, 0)
    assert s.requeue_task(t, "e1", "primary lost", limit=1)
    assert speculation_stats().get("promoted") == 1
    # the watch clock keeps the duplicate's LAUNCH time: its completion
    # must observe the true duration, not seconds-since-promotion
    assert s._running_since[("j", 1, 0)] == ("e2", 1, spec_t0)
    cur = s.get_task_status("j", 1, 0)
    assert cur.WhichOneof("status") == "running"
    assert cur.attempt == 1 and cur.speculative
    assert cur.running.executor_id == "e2"
    assert len(cur.history) == 1 and cur.history[0].error == "primary lost"
    # speculation record retired into a normal assignment-ledger entry
    assert s.kv.get(SPEC_KEY) is None
    raw = s.kv.get("/ballista/t/assignments/j/1/0")
    assert raw is not None
    a = pb.Assignment()
    a.ParseFromString(raw)
    assert a.executor_id == "e2" and a.attempt == 1
    # the promoted attempt completes like any other
    assert s.accept_task_status(_completed("j", 1, 0, 1, "e2", speculative=True))


def test_lineage_invalidation_retires_instead_of_promoting():
    """Review regression: a requeue caused by the task's UPSTREAM
    locations dying (lineage invalidation / fetch_failed) must NOT promote
    the duplicate — it was bound to the same dead locations; plain requeue
    rebinds fresh ones at the next assignment."""
    speculation_stats(reset=True)
    s = _straggling_state()
    assert s.maybe_speculate("e2") is not None
    t = s.get_task_status("j", 1, 0)
    assert s.requeue_task(
        t, "e1", "upstream shuffle locations lost mid-run", limit=3,
        promote=False,
    )
    stats = speculation_stats()
    assert stats.get("promoted", 0) == 0
    assert stats.get("failed") == 1  # the duplicate retired with the reset
    assert s.kv.get(SPEC_KEY) is None
    cur = s.get_task_status("j", 1, 0)
    # pending, numbered PAST the retired duplicate's attempt 1 (ISSUE 15:
    # the retired duplicate may still be running — a same-number requeue
    # would let its late report impersonate the fresh attempt)
    assert cur.WhichOneof("status") is None and cur.attempt == 2


def test_push_status_suppresses_unchanged_rewrites():
    """Review regression: one push per TRANSITION — synchronize's
    byte-identical running re-writes (one per non-final task completion)
    must not wake every SubscribeJobStatus subscriber."""
    import threading

    from ballista_tpu.scheduler.server import SchedulerServer

    costmodel.reset()
    srv = SchedulerServer(MemoryBackend(), config=_spec_config())
    running = pb.JobStatus()
    running.running.SetInParent()
    srv.state.save_job_metadata("j", running)
    stream = srv.SubscribeJobStatus(pb.GetJobStatusParams(job_id="j"))
    got = []

    def consume():
        for res in stream:
            got.append(res.status.WhichOneof("status"))

    th = threading.Thread(target=consume, daemon=True)
    th.start()
    time.sleep(0.1)
    srv.state.save_job_metadata("j", running)  # identical: suppressed
    srv.state.save_job_metadata("j", running)  # identical: suppressed
    done = pb.JobStatus()
    done.completed.SetInParent()
    srv.state.save_job_metadata("j", done)  # transition: pushed, terminal
    th.join(5)
    assert not th.is_alive()
    assert got == ["running", "completed"], got


def test_redelivered_completion_stays_idempotent():
    """Review regression: the completion-stands guard must NOT drop a
    redelivery of the SAME completion (same attempt, same executor) — a
    scheduler crash between accepting a job's final status and the
    job-status fold makes the executor redeliver it, and dropping it would
    wedge the job in running forever."""
    s = _straggling_state()
    done = _completed("j", 1, 0, 0, "e1")
    assert s.accept_task_status(done)
    # exact redelivery (post-crash requeue): accepted, so the caller
    # re-enters the job into the synchronize set
    assert s.accept_task_status(_completed("j", 1, 0, 0, "e1"))
    # a DIFFERENT completion for the resolved task still drops: another
    # executor's racing report must not clobber the published location
    assert not s.accept_task_status(_completed("j", 1, 0, 0, "e2"))
    cur = s.get_task_status("j", 1, 0)
    assert cur.completed.executor_id == "e1"


def test_promotion_respects_the_retry_budget():
    """Review regression: a primary already AT its final allowed attempt
    must fail the job when it dies — the in-flight duplicate is retired,
    never promoted to attempt numbers past the configured limit."""
    speculation_stats(reset=True)
    s = _straggling_state()
    assert s.maybe_speculate("e2") is not None
    t = s.get_task_status("j", 1, 0)
    # limit 0: attempt 0 IS the final budgeted attempt
    assert not s.requeue_task(t, "e1", "primary lost", limit=0)
    stats = speculation_stats()
    assert stats.get("promoted", 0) == 0
    assert stats.get("failed") == 1
    assert s.kv.get(SPEC_KEY) is None  # duplicate record retired with the job


# -- crash + restart recovery -----------------------------------------------


def test_restart_recovers_both_attempts_from_the_ledger(tmp_path):
    """ISSUE 11 acceptance: a scheduler crash mid-speculation recovers the
    primary (assignment ledger + running status) AND the duplicate
    (speculation ledger); the owners' echoes re-adopt both, and the pair
    then resolves through first-completion-wins exactly as if the crash
    never happened."""
    db = str(tmp_path / "state.db")
    s1 = _straggling_state(kv=SqliteBackend(db))
    assert s1.maybe_speculate("e2") is not None
    del s1  # crash with both attempts in flight

    recovery_stats(reset=True)
    speculation_stats(reset=True)
    s2 = SchedulerState(SqliteBackend(db), "t", config=_spec_config())
    stats = s2.recover()
    assert stats.get("restart_assignment_restored") == 1
    assert stats.get("restart_speculation_restored") == 1
    assert speculation_stats().get("restored") == 1
    assert ("j", 1, 0) in s2._assigned
    assert s2.speculation_active(("j", 1, 0), "e2", 1)
    # both owners vouch: nothing requeues, the duplicate is re-adopted
    assert s2.reconcile_running_tasks("e1", [_echo("j", 1, 0, 0)]) == 0
    assert s2.reconcile_running_tasks("e2", [_echo("j", 1, 0, 1)]) == 0
    assert recovery_stats().get("restart_speculation_readopted") == 1
    # the race resolves normally after the restart: duplicate wins here
    assert s2.accept_task_status(_completed("j", 1, 0, 1, "e2", speculative=True))
    assert not s2.accept_task_status(_completed("j", 1, 0, 0, "e1"))
    cur = s2.get_task_status("j", 1, 0)
    assert cur.attempt == 1 and cur.completed.executor_id == "e2"
    assert s2.kv.get(SPEC_KEY) is None


def test_restart_sweeps_stale_speculation_records(tmp_path):
    """A speculation record whose primary already resolved (or advanced to
    another attempt) is leftover, not live — restart deletes it instead of
    resurrecting a ghost duplicate."""
    db = str(tmp_path / "state.db")
    s1 = _straggling_state(kv=SqliteBackend(db))
    assert s1.maybe_speculate("e2") is not None
    # the primary completes BEFORE the crash... but the crash interleaves
    # with the ledger cleanup: re-write the stale record under the key
    assert s1.accept_task_status(_completed("j", 1, 0, 0, "e1"))
    msg = pb.Assignment(executor_id="e2", attempt=1)
    s1.kv.put(SPEC_KEY, msg.SerializeToString())
    del s1

    s2 = SchedulerState(SqliteBackend(db), "t", config=_spec_config())
    stats = s2.recover()
    assert stats.get("restart_speculation_restored", 0) == 0
    assert s2.kv.get(SPEC_KEY) is None
    assert not s2._speculative


def test_lost_in_transit_duplicate_is_dropped_after_grace():
    """The duplicate has no tasks/ status, so a delivery lost in transit is
    only visible to the speculation ledger: unvouched past the grace
    window, the record is dropped — the primary still runs, nothing
    requeues."""
    speculation_stats(reset=True)
    s = _straggling_state()
    assert s.maybe_speculate("e2") is not None
    ex, at, t0, vouched, restored = s._speculative[("j", 1, 0)]
    s._speculative[("j", 1, 0)] = (ex, at, t0 - 60.0, vouched, restored)
    # e2 polls with an empty echo: it never received the duplicate
    s.reconcile_running_tasks("e2", [])
    assert speculation_stats().get("orphaned") == 1
    assert s.kv.get(SPEC_KEY) is None
    cur = s.get_task_status("j", 1, 0)
    assert cur.WhichOneof("status") == "running" and cur.attempt == 0


def test_dead_duplicate_executor_retires_the_speculation():
    """The duplicate's executor lease lapses: the sweep in the straggler
    monitor drops the record (the primary still runs) and the task may
    speculate again onto a live executor."""
    speculation_stats(reset=True)
    s = _straggling_state()
    assert s.maybe_speculate("e2") is not None
    s.kv.delete("/ballista/t/executors/e2")  # lease gone
    s.save_executor_metadata(_meta("e3"))
    owner, attempt, t0 = s._running_since[("j", 1, 0)]
    s._running_since[("j", 1, 0)] = (owner, attempt, t0 - 5.0)
    got = s.maybe_speculate("e3")
    assert speculation_stats().get("executor_lost") == 1
    assert got is not None and got[0].attempt == 1
    raw = s.kv.get(SPEC_KEY)
    a = pb.Assignment()
    a.ParseFromString(raw)
    assert a.executor_id == "e3"


# -- per-tenant latency SLOs ------------------------------------------------


def _scan_stage(n_parts=2):
    """A real single-stage plan so assignment can bind it."""
    from ballista_tpu.distributed.planner import DistributedPlanner
    from ballista_tpu.engine import ExecutionContext
    from ballista_tpu.logical import col

    ctx = ExecutionContext()
    ctx.register_record_batches(
        "t", pa.table({"g": ["a", "b"], "v": [1.0, 2.0]}), n_partitions=n_parts
    )
    df = ctx.table("t").select(col("g"))
    physical = ctx.create_physical_plan(df.logical_plan())
    stages = DistributedPlanner().plan_query_stages("job", physical)
    return stages[0]


def test_tenant_slo_parsing():
    cfg = BallistaConfig({"ballista.tenant.slo_ms": "alice:250, bob:2000"})
    assert cfg.tenant_slos() == {"alice": 250.0, "bob": 2000.0}
    assert BallistaConfig().tenant_slos() == {}
    with pytest.raises(ValueError):
        BallistaConfig({"ballista.tenant.slo_ms": "250"}).tenant_slos()


def test_overdue_tenant_jumps_the_fair_share_order():
    """Deadline-aware admission: pure fair share would hand the idle
    tenant's task out next, but the busy tenant's oldest pending job has
    blown its SLO deadline — it is visited first."""
    from ballista_tpu.ops.runtime import tenancy_stats

    costmodel.reset()
    s = SchedulerState(
        MemoryBackend(), "t",
        config=_spec_config(**{"ballista.tenant.slo_ms": "alice:100"}),
    )
    s.save_executor_metadata(_meta("e1"))
    stage_a = _scan_stage(3)
    s.save_job_tenant("aj", "alice", 0, created_at=time.time() - 10.0)
    s.save_stage_plan("aj", stage_a.stage_id, stage_a)
    for p in range(3):
        s.save_task_status(_pending("aj", stage_a.stage_id, p))
    stage_b = _scan_stage(1)
    s.save_job_tenant("bj", "bob", 0)
    s.save_stage_plan("bj", stage_b.stage_id, stage_b)
    s.save_task_status(_pending("bj", stage_b.stage_id, 0))
    tenancy_stats(reset=True)
    # alice takes the first slot (tie or boost), then the fair-share ratio
    # (1 in flight vs bob's 0) would prefer bob — the blown deadline keeps
    # alice ahead until her pending work drains
    got = [
        s.job_tenant(
            s.assign_next_schedulable_task("e1")[0].partition_id.job_id
        )[0]
        for _ in range(3)
    ]
    assert got == ["alice", "alice", "alice"], got
    # one sustained overdue condition is ONE boost episode, however many
    # admission scans it spans
    assert tenancy_stats().get("admit_slo_boosted", 0) == 1
    # with no SLO configured the same shape hands bob the second slot
    costmodel.reset()
    s2 = SchedulerState(MemoryBackend(), "t", config=_spec_config())
    s2.save_executor_metadata(_meta("e1"))
    s2.save_job_tenant("aj", "alice", 0, created_at=time.time() - 10.0)
    s2.save_stage_plan("aj", stage_a.stage_id, stage_a)
    for p in range(3):
        s2.save_task_status(_pending("aj", stage_a.stage_id, p))
    s2.save_job_tenant("bj", "bob", 0)
    s2.save_stage_plan("bj", stage_b.stage_id, stage_b)
    s2.save_task_status(_pending("bj", stage_b.stage_id, 0))
    got2 = [
        s2.job_tenant(
            s2.assign_next_schedulable_task("e1")[0].partition_id.job_id
        )[0]
        for _ in range(2)
    ]
    assert got2 == ["alice", "bob"], got2


def test_slo_outcome_counters():
    speculation_stats(reset=True)
    costmodel.reset()
    s = SchedulerState(
        MemoryBackend(), "t",
        config=_spec_config(**{"ballista.tenant.slo_ms": "alice:100"}),
    )
    s.save_job_tenant("late", "alice", 0, created_at=time.time() - 10.0)
    s._note_job_slo("late")
    s.save_job_tenant("fast", "alice", 0, created_at=time.time())
    s._note_job_slo("fast")
    # no SLO for this tenant: no outcome recorded either way
    s.save_job_tenant("other", "carol", 0, created_at=time.time() - 10.0)
    s._note_job_slo("other")
    # one job is ONE outcome: a re-fold (restart_completed_job after a
    # lost result partition) must not double-count
    s._note_job_slo("late")
    stats = speculation_stats()
    assert stats.get("slo_misses") == 1
    assert stats.get("slo_met") == 1


# -- whole-stage cost predictions scale with input (PR 10 residue) ----------


def test_stage_run_units_scale_with_input(tmp_path):
    """Pre-fix-failing (ISSUE 11 satellite): stage.run observations must be
    normalized by the stage's input size (memory-scan rows / leaf-file
    bytes), not units=1 — a unit-less rate memorizes one run's seconds and
    guarantees a gross mispredict the first time the same stage shape runs
    at a new scale. Speculation thresholds consume these predictions
    directly."""
    from ballista_tpu.engine import ExecutionContext

    costmodel.reset(clear_dir=True)
    n = 512
    ctx = ExecutionContext(BallistaConfig({
        "ballista.executor.backend": "tpu",
        "ballista.tpu.cost_model_dir": str(tmp_path),
    }))
    ctx.register_record_batches(
        "t",
        pa.table({
            "g": pa.array([f"g{i % 7}" for i in range(n)]),
            "v": pa.array([float(i) for i in range(n)]),
        }),
        n_partitions=1,
    )
    out = ctx.sql("select g, sum(v) as s from t group by g order by g").collect()
    assert out.num_rows == 7
    entries = {
        k: v for k, v in costmodel.snapshot().items()
        if k.startswith("stage.run|")
    }
    assert entries, "no stage.run observation recorded"
    assert any(v["units"] >= n for v in entries.values()), (
        f"stage.run observed with scale-blind units: {entries}"
    )
    costmodel.reset(clear_dir=True)


# -- end-to-end: seeded straggler rescued, bit-identical --------------------


def test_speculation_rescues_seeded_straggler_end_to_end():
    """ISSUE 11 acceptance (cluster-level): a seeded `task.slow` straggler
    in a real 2-executor cluster is rescued by a speculative duplicate —
    the job completes long before the injected delay elapses, the
    duplicate's completion wins, and the result is bit-identical to the
    fault-free run."""
    import numpy as np

    from ballista_tpu.client import BallistaContext
    from ballista_tpu.executor.runtime import StandaloneCluster

    rng = np.random.default_rng(1101)
    n = 4000
    table = pa.table({
        "g": pa.array(rng.integers(0, 23, n), type=pa.int64()),
        "v": pa.array(np.round(rng.uniform(-100, 100, n), 2)),
    })
    sql = "select g, sum(v) as s, count(*) as n from t group by g order by g"
    base_client = {
        "ballista.shuffle.partitions": "2",
        "ballista.cache.results": "false",
        "ballista.tpu.cost_model_dir": "",
    }
    costmodel.reset()
    cluster = StandaloneCluster(
        n_executors=2,
        config=BallistaConfig({
            "ballista.tpu.cost_model_dir": "",
            "ballista.speculation.min_runtime_ms": "150",
            "ballista.speculation.multiplier": "3",
        }),
    )
    try:
        ctx = BallistaContext(*cluster.scheduler_addr, settings=base_client)
        ctx.register_record_batches("t", table, n_partitions=6)
        clean = ctx.sql(sql).collect()
        ctx.close()
        # harvest the executed plan coordinates: chaos verdicts are keyed
        # on (stage, partition, attempt), never job ids, so the clean run's
        # layout predicts the chaos run's exactly
        st = cluster.scheduler_impl.state
        coords = []
        for k, _v in st.kv.get_prefix(st._key("tasks")):
            tail = k.rsplit("/", 3)
            coords.append((int(tail[2]), int(tail[3])))
        by_stage = {}
        for c in coords:
            by_stage.setdefault(c[0], []).append(c)
        # pick a seed injecting EXACTLY one straggler, in a stage with
        # enough fast siblings to warm the prediction past
        # MIN_OBSERVATIONS, whose duplicate (attempt 1) draws fast
        RATE = 0.12
        seed = None
        for cand in range(2000):
            inj = ChaosInjector(cand, RATE, sites=("task.slow",))
            slow = [
                c for c in coords
                if inj.should_inject("task.slow", f"{c[0]}/{c[1]}@a0")
            ]
            if (
                len(slow) == 1
                and len(by_stage[slow[0][0]]) >= costmodel.MIN_OBSERVATIONS + 1
                and not inj.should_inject(
                    "task.slow", f"{slow[0][0]}/{slow[0][1]}@a1"
                )
            ):
                seed = cand
                break
        assert seed is not None, "no qualifying chaos seed in range"
        speculation_stats(reset=True)
        ctx2 = BallistaContext(
            *cluster.scheduler_addr,
            settings={
                **base_client,
                "ballista.chaos.rate": str(RATE),
                "ballista.chaos.seed": str(seed),
                "ballista.chaos.sites": "task.slow",
                "ballista.chaos.slow_ms": "4000",
            },
        )
        ctx2.register_record_batches("t", table, n_partitions=6)
        t0 = time.perf_counter()
        chaotic = ctx2.sql(sql).collect()
        dt = time.perf_counter() - t0
        ctx2.close()
        assert chaotic.equals(clean), (
            chaotic.to_pydict(), clean.to_pydict(),
        )
        stats = speculation_stats(reset=True)
        assert stats.get("launched", 0) >= 1, stats
        assert stats.get("won", 0) >= 1, stats
        # the rescue is the point: the job must finish well inside the
        # straggler's injected 4s delay
        assert dt < 3.5, f"speculation did not rescue the tail: {dt:.2f}s"
    finally:
        cluster.shutdown()
        costmodel.reset()


# -- elapsed-ordered straggler heap (ISSUE 13 satellite, PR 11 residue) ------


def test_straggler_heap_agrees_with_linear_scan():
    """The heap-backed candidate walk must return exactly what the old
    linear scan of _running_since would: every running task past the
    speculation floor, most-elapsed first — including entries whose watch
    clocks were re-stamped after their heap push (the reconcile path)."""
    import numpy as np

    cfg = _spec_config(**{"ballista.speculation.min_runtime_ms": "1000"})
    s = SchedulerState(MemoryBackend(), "t", config=cfg)
    _running_job(s)
    s.save_executor_metadata(_meta("e1"))
    rng = np.random.default_rng(7)
    ages = {}
    for p in range(24):
        t = _pending("j", 1, p)
        t.running.executor_id = "e1"
        s.save_task_status(t)
        # back-date like the promotion re-stamp does: rewrite the watch
        # clock AND push the corrected entry (the superseded heap entry
        # reconciles/dedupes lazily)
        import heapq

        age = float(rng.choice([0.0, 0.2, 0.9, 1.1, 2.5, 7.0, 30.0]))
        owner, attempt, t0 = s._running_since[("j", 1, p)]
        s._running_since[("j", 1, p)] = (owner, attempt, t0 - age)
        heapq.heappush(s._running_heap, (t0 - age, ("j", 1, p)))
        ages[("j", 1, p)] = age
    now = time.monotonic()

    def linear_reference():
        out = [
            k for k, e in s._running_since.items()
            if now - e[2] >= s._spec_floor_s
        ]
        out.sort(key=lambda k: s._running_since[k][2])  # oldest first
        return out

    got = s._straggler_candidates(now)
    assert got == linear_reference(), (got, linear_reference())
    assert got, "the synthetic ages must produce candidates"
    # repeated calls are stable: floor-passing entries re-push on exit
    assert s._straggler_candidates(now) == got
    # resolving a task removes it from candidates (lazy heap invalidation)
    victim = got[0]
    done = _completed(*victim, attempt=0, executor="e1")
    s.save_task_status(done)
    rest = s._straggler_candidates(now)
    assert victim not in rest and rest == [k for k in got if k != victim]


def test_straggler_heap_early_exits_on_young_tasks():
    """An idle slot on a healthy cluster (every running task younger than
    the floor) must not sweep the watch map: the t0-ordered heap walk
    breaks at the first young entry and returns nothing."""
    cfg = _spec_config(**{"ballista.speculation.min_runtime_ms": "60000"})
    s = SchedulerState(MemoryBackend(), "t", config=cfg)
    _running_job(s)
    s.save_executor_metadata(_meta("e1"))
    for p in range(8):
        t = _pending("j", 1, p)
        t.running.executor_id = "e1"
        s.save_task_status(t)
    assert s._straggler_candidates(time.monotonic()) == []
    # the heap survives the walk intact for the next slot
    assert len(s._running_heap) == 8


# -- re-speculation (ISSUE 15 satellite, PR 11 residue) ----------------------


def _age_live_duplicate(s, seconds=5.0, key=("j", 1, 0)):
    ex, at, t0, v, r = s._speculative[key]
    s._speculative[key] = (ex, at, t0 - seconds, v, r)


def test_respeculation_supersedes_straggling_duplicate():
    """A duplicate that ITSELF straggles past the same cost-model threshold
    is superseded by a fresh duplicate on a third executor: the ledger now
    tracks attempt 2, the abandoned attempt 1 lands in the superseded set,
    and the launch count enforces ballista.speculation.max_attempts."""
    speculation_stats(reset=True)
    s = _straggling_state()
    s.save_executor_metadata(_meta("e3"))
    s.save_executor_metadata(_meta("e4"))
    assert s.maybe_speculate("e2") is not None
    # age the LIVE duplicate's launch clock so it reads as a straggler
    # against the warm ~1ms rate (the judgment is on its own clock)
    _age_live_duplicate(s)
    got = s.maybe_speculate("e3")
    assert got is not None
    dup, _plan = got
    assert dup.attempt == 2 and dup.speculative
    raw = s.kv.get(SPEC_KEY)
    a = pb.Assignment()
    a.ParseFromString(raw)
    assert a.executor_id == "e3" and a.attempt == 2
    assert s._spec_superseded[("j", 1, 0)] == {1}
    assert s._spec_launches[("j", 1, 0)] == 2
    stats = speculation_stats()
    assert stats.get("launched") == 2 and stats.get("relaunched") == 1
    # bounded: max_attempts=2 (default) — a third launch never happens,
    # however long the second duplicate straggles
    _age_live_duplicate(s)
    assert s.maybe_speculate("e4") is None


def test_respeculation_bounded_by_max_attempts_one():
    """ballista.speculation.max_attempts=1 restores launch-once exactly."""
    s = _straggling_state(
        config=_spec_config(**{"ballista.speculation.max_attempts": "1"})
    )
    s.save_executor_metadata(_meta("e3"))
    assert s.maybe_speculate("e2") is not None
    _age_live_duplicate(s)
    assert s.maybe_speculate("e3") is None


def test_respeculation_waits_for_the_duplicate_floor():
    """The duplicate is judged on ITS OWN clock: a fresh duplicate (under
    the floor) is never superseded even while the primary's elapsed time
    screams straggler."""
    s = _straggling_state(
        config=_spec_config(**{"ballista.speculation.min_runtime_ms": "60000"})
    )
    s.save_executor_metadata(_meta("e3"))
    # age the PRIMARY past the (huge) floor so the first launch fires
    owner, attempt, t0 = s._running_since[("j", 1, 0)]
    s._running_since[("j", 1, 0)] = (owner, attempt, t0 - 120.0)
    assert s.maybe_speculate("e2") is not None
    # the duplicate is brand new: primary still ancient, duplicate under
    # its own floor -> no re-speculation
    assert s.maybe_speculate("e3") is None


def test_superseded_failure_spares_task_and_live_duplicate():
    """An abandoned duplicate's failure touches nothing: no retry budget
    consumed, the primary stays running, and the LIVE successor duplicate
    stays ledgered."""
    speculation_stats(reset=True)
    s = _straggling_state()
    s.save_executor_metadata(_meta("e3"))
    assert s.maybe_speculate("e2") is not None
    _age_live_duplicate(s)
    assert s.maybe_speculate("e3") is not None
    failed = _pending("j", 1, 0, attempt=1)
    failed.speculative = True
    failed.failed.error = "boom"
    failed.failed.executor_id = "e2"
    assert s.accept_task_status(failed) is False
    cur = s.get_task_status("j", 1, 0)
    assert cur.WhichOneof("status") == "running" and cur.attempt == 0
    a = pb.Assignment()
    a.ParseFromString(s.kv.get(SPEC_KEY))
    assert a.executor_id == "e3" and a.attempt == 2
    stats = speculation_stats()
    assert stats.get("superseded_failed") == 1
    assert ("j", 1, 0) not in s._spec_superseded  # retired on sight


def test_superseded_completion_still_wins():
    """First completion wins, whoever crosses the line: the ABANDONED
    duplicate finishing first resolves the task, and the whole episode
    (ledger + superseded set) closes."""
    speculation_stats(reset=True)
    s = _straggling_state()
    s.save_executor_metadata(_meta("e3"))
    assert s.maybe_speculate("e2") is not None
    _age_live_duplicate(s)
    assert s.maybe_speculate("e3") is not None
    done = _completed("j", 1, 0, attempt=1, executor="e2", speculative=True)
    assert s.accept_task_status(done) is True
    cur = s.get_task_status("j", 1, 0)
    assert cur.WhichOneof("status") == "completed"
    assert cur.completed.executor_id == "e2"
    assert s.kv.get(SPEC_KEY) is None
    assert ("j", 1, 0) not in s._spec_superseded
    assert ("j", 1, 0) not in s._spec_launches
    stats = speculation_stats()
    assert stats.get("superseded_won") == 1
    # review regression: the abandoned duplicate's rescue is a speculative
    # WIN in the effectiveness counters, never a "primary won" loss
    assert stats.get("won") == 1, stats
    assert stats.get("lost", 0) == 0, stats


def test_requeue_numbers_past_every_minted_speculative_attempt():
    """A requeue after re-speculation numbers PAST the highest minted
    duplicate attempt (ledgered AND superseded), so no late report from an
    abandoned attempt can impersonate the fresh one."""
    s = _straggling_state()
    s.save_executor_metadata(_meta("e3"))
    assert s.maybe_speculate("e2") is not None
    _age_live_duplicate(s)
    assert s.maybe_speculate("e3") is not None  # ledger at attempt 2
    t = s.get_task_status("j", 1, 0)
    assert s.requeue_task(t, "e1", "upstream lost", limit=5, promote=False)
    cur = s.get_task_status("j", 1, 0)
    assert cur.WhichOneof("status") is None and cur.attempt == 3


def test_primary_failure_promotes_the_respeculated_duplicate():
    """Primary dies while the RE-speculated duplicate runs: the promotion
    path adopts it (attempt 2, on its executor) exactly like a first-round
    duplicate — no retry budget consumed."""
    speculation_stats(reset=True)
    s = _straggling_state()
    s.save_executor_metadata(_meta("e3"))
    assert s.maybe_speculate("e2") is not None
    _age_live_duplicate(s)
    assert s.maybe_speculate("e3") is not None
    t = s.get_task_status("j", 1, 0)
    assert s.requeue_task(t, "e1", "primary lost", limit=3)
    cur = s.get_task_status("j", 1, 0)
    assert cur.WhichOneof("status") == "running"
    assert cur.attempt == 2 and cur.running.executor_id == "e3"
    assert speculation_stats().get("promoted") == 1
    # promoted into the ASSIGNMENT ledger; speculation record retired
    assert s.kv.get(SPEC_KEY) is None
    assert s.kv.get("/ballista/t/assignments/j/1/0") is not None


def test_restart_recovers_respeculated_duplicate(tmp_path):
    """A scheduler restart mid-re-speculation restores the ledgered
    attempt-2 duplicate (primary still running attempt 0) and rebuilds the
    launch bound from attempt arithmetic, so the restarted scheduler never
    launches past max_attempts either."""
    kv = SqliteBackend(str(tmp_path / "led.db"))
    s = _straggling_state(kv=kv)
    s.save_executor_metadata(_meta("e3"))
    s.save_executor_metadata(_meta("e4"))
    assert s.maybe_speculate("e2") is not None
    _age_live_duplicate(s)
    assert s.maybe_speculate("e3") is not None
    s2 = SchedulerState(kv, "t", config=_spec_config())
    stats = s2.recover()
    assert stats.get("restart_speculation_restored") == 1, stats
    assert s2._speculative[("j", 1, 0)][0] == "e3"
    assert s2._speculative[("j", 1, 0)][1] == 2
    assert s2._spec_launches[("j", 1, 0)] == 2
    # at the bound: the restarted scheduler refuses a third launch. It has
    # no watch entry until statuses flow — seed one (aged, warm rate) so
    # the monitor WOULD fire if the launch bound did not hold.
    import heapq
    import time as _time

    _age_live_duplicate(s2)
    costmodel.seed(s2._task_run_op("j", 1), 1.0, 0.001, engine="task")
    s2._running_since[("j", 1, 0)] = ("e1", 0, _time.monotonic() - 5.0)
    heapq.heappush(
        s2._running_heap, (s2._running_since[("j", 1, 0)][2], ("j", 1, 0))
    )
    assert s2.maybe_speculate("e4") is None


def test_respeculation_rescues_double_straggler_end_to_end():
    """ISSUE 15 satellite acceptance (cluster-level): a seed where BOTH the
    primary (attempt 0) and the first duplicate (attempt 1) draw slow
    `task.slow` verdicts, while attempt 2 draws fast — the re-speculated
    second duplicate rescues the tail: the job finishes well inside the
    injected delay, a relaunch is counted, and the result is bit-identical
    to the fault-free run. Needs 3 executors: the re-speculation never
    lands on the primary's or the live duplicate's executor."""
    import numpy as np

    from ballista_tpu.client import BallistaContext
    from ballista_tpu.executor.runtime import StandaloneCluster

    rng = np.random.default_rng(1103)
    n = 4000
    table = pa.table({
        "g": pa.array(rng.integers(0, 23, n), type=pa.int64()),
        "v": pa.array(np.round(rng.uniform(-100, 100, n), 2)),
    })
    sql = "select g, sum(v) as s, count(*) as n from t group by g order by g"
    base_client = {
        "ballista.shuffle.partitions": "2",
        "ballista.cache.results": "false",
        "ballista.tpu.cost_model_dir": "",
    }
    costmodel.reset()
    cluster = StandaloneCluster(
        n_executors=3,
        config=BallistaConfig({
            "ballista.tpu.cost_model_dir": "",
            "ballista.speculation.min_runtime_ms": "150",
            "ballista.speculation.multiplier": "3",
            "ballista.speculation.max_attempts": "2",
        }),
    )
    try:
        ctx = BallistaContext(*cluster.scheduler_addr, settings=base_client)
        ctx.register_record_batches("t", table, n_partitions=6)
        clean = ctx.sql(sql).collect()
        ctx.close()
        st = cluster.scheduler_impl.state
        coords = []
        for k, _v in st.kv.get_prefix(st._key("tasks")):
            tail = k.rsplit("/", 3)
            coords.append((int(tail[2]), int(tail[3])))
        by_stage = {}
        for c in coords:
            by_stage.setdefault(c[0], []).append(c)
        # seed injecting EXACTLY one straggler coordinate whose attempts 0
        # AND 1 are both slow and attempt 2 is fast, in a stage with
        # enough fast siblings to warm the prediction
        RATE = 0.12
        seed = None
        for cand in range(4000):
            inj = ChaosInjector(cand, RATE, sites=("task.slow",))
            slow = [
                c for c in coords
                if inj.should_inject("task.slow", f"{c[0]}/{c[1]}@a0")
            ]
            if (
                len(slow) == 1
                and len(by_stage[slow[0][0]]) >= costmodel.MIN_OBSERVATIONS + 1
                and inj.should_inject(
                    "task.slow", f"{slow[0][0]}/{slow[0][1]}@a1"
                )
                and not inj.should_inject(
                    "task.slow", f"{slow[0][0]}/{slow[0][1]}@a2"
                )
            ):
                seed = cand
                break
        assert seed is not None, "no qualifying chaos seed in range"
        speculation_stats(reset=True)
        ctx2 = BallistaContext(
            *cluster.scheduler_addr,
            settings={
                **base_client,
                "ballista.chaos.rate": str(RATE),
                "ballista.chaos.seed": str(seed),
                "ballista.chaos.sites": "task.slow",
                "ballista.chaos.slow_ms": "8000",
            },
        )
        ctx2.register_record_batches("t", table, n_partitions=6)
        t0 = time.perf_counter()
        chaotic = ctx2.sql(sql).collect()
        dt = time.perf_counter() - t0
        ctx2.close()
        assert chaotic.equals(clean), (
            chaotic.to_pydict(), clean.to_pydict(),
        )
        stats = speculation_stats(reset=True)
        assert stats.get("launched", 0) >= 2, stats
        assert stats.get("relaunched", 0) >= 1, stats
        assert stats.get("won", 0) >= 1, stats
        # the rescue: both slow attempts carried an 8s injected delay; the
        # re-speculated attempt finishes far inside it
        assert dt < 7.0, f"re-speculation did not rescue the tail: {dt:.2f}s"
    finally:
        cluster.shutdown()
        costmodel.reset()

"""Native (C++) shuffle kernel tests: build, bit-equality with the numpy
path, and the counting-sort splitter."""

import numpy as np
import pyarrow as pa
import pytest

from ballista_tpu.native import (
    get_lib,
    native_hash_rows,
    native_partition_indices,
)


@pytest.fixture(scope="module")
def lib():
    l = get_lib()
    if l is None:
        pytest.skip("no C++ toolchain available")
    return l


def _numpy_hash(arrays, n_parts):
    # force the numpy reference path
    import ballista_tpu.physical.repartition as rp

    n = len(arrays[0])
    acc = np.zeros(n, dtype=np.uint64)
    import pyarrow.compute as pc

    for arr in arrays:
        a = arr
        if pa.types.is_date32(a.type):
            a = a.cast(pa.int32())
        if pa.types.is_integer(a.type) or pa.types.is_boolean(a.type):
            vals = pc.cast(a, pa.int64()).to_numpy(zero_copy_only=False).astype(np.int64)
            h = rp._splitmix64(vals.view(np.uint64))
        elif pa.types.is_floating(a.type):
            vals = a.to_numpy(zero_copy_only=False)
            h = rp._splitmix64(np.asarray(vals, dtype=np.float64).view(np.uint64))
        else:
            h = np.empty(n, dtype=np.uint64)
            for i, v in enumerate(a.to_pylist()):
                acc2 = np.uint64(0xCBF29CE484222325)
                for b in str(v).encode():
                    acc2 = np.uint64((int(acc2) ^ b) * 0x100000001B3 & 0xFFFFFFFFFFFFFFFF)
                h[i] = acc2
        acc = rp._splitmix64(acc ^ h)
    return (acc % np.uint64(n_parts)).astype(np.int64)


@pytest.mark.parametrize(
    "col",
    [
        pa.array(np.arange(1000, dtype=np.int64) * 7919 - 500),
        pa.array(np.random.default_rng(0).uniform(-10, 10, 1000)),
        pa.array([f"key_{i % 37}" for i in range(1000)]),
        pa.array(np.arange(1000, dtype=np.int32), type=pa.int32()),
    ],
    ids=["int64", "float64", "string", "int32"],
)
def test_native_matches_numpy(lib, col):
    got = native_hash_rows([col], 16)
    want = _numpy_hash([col], 16)
    assert got is not None
    np.testing.assert_array_equal(got.astype(np.int64), want)


def test_native_composite_keys(lib):
    cols = [
        pa.array(np.arange(500, dtype=np.int64)),
        pa.array([f"s{i % 5}" for i in range(500)]),
    ]
    got = native_hash_rows(cols, 8)
    want = _numpy_hash(cols, 8)
    np.testing.assert_array_equal(got.astype(np.int64), want)


def test_partition_indices(lib):
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 7, 10_000).astype(np.int32)
    indices, offsets = native_partition_indices(ids, 7)
    assert offsets[0] == 0 and offsets[-1] == len(ids)
    for p in range(7):
        seg = indices[offsets[p]: offsets[p + 1]]
        assert (ids[seg] == p).all()
        # stable order within partition
        assert (np.diff(seg) > 0).all()
    # every row exactly once
    assert sorted(indices.tolist()) == list(range(len(ids)))


def test_split_by_partition_roundtrip():
    from ballista_tpu.physical.repartition import split_by_partition

    batch = pa.record_batch(
        {"k": pa.array(np.arange(100, dtype=np.int64)), "v": pa.array(np.arange(100) * 1.5)}
    )
    ids = (np.arange(100) * 13 % 5).astype(np.int64)
    pieces = split_by_partition(batch, ids, 5)
    assert sum(p.num_rows for p in pieces) == 100
    for m, piece in enumerate(pieces):
        ks = piece.column("k").to_numpy()
        assert ((ks * 13 % 5) == m).all()

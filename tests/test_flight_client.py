"""BallistaClient (Flight wrapper) tests: the push-based ExecutePartition
path (ref BallistaClient::execute_partition) and fetch_partition."""

import os

import pyarrow as pa
import pytest

from ballista_tpu.client.flight import BallistaClient
from ballista_tpu.errors import RpcError


def test_prelude_imports():
    import ballista_tpu.prelude as p

    assert p.col("x").name == "x"
    assert callable(p.functions.sum)


def test_execute_and_fetch_partition(sales_table, tmp_path):
    from ballista_tpu.engine import ExecutionContext

    svc, port = _serve(tmp_path)

    # build a plan locally and push it to the executor
    ctx = ExecutionContext()
    ctx.register_record_batches("sales", sales_table, n_partitions=2)
    from ballista_tpu.logical import col, functions as F

    df = ctx.table("sales").aggregate([], [F.sum(col("amount")).alias("s")])
    physical = ctx.create_physical_plan(df.logical_plan())

    client = BallistaClient("127.0.0.1", port)
    results = client.execute_partition("jobf", 1, [0], physical)
    assert len(results) == 1
    path, stats = results[0]
    assert stats.num_rows == 1

    fetched = client.fetch_partition(os.path.join(path, "0.arrow"))
    assert fetched.column("s").to_pylist() == [305.0]
    client.close()
    svc.shutdown()


def _serve(tmp_path):
    import socket
    import threading

    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.executor.flight_service import BallistaFlightService

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    svc = BallistaFlightService(
        f"grpc://0.0.0.0:{port}", str(tmp_path), BallistaConfig()
    )
    threading.Thread(target=svc.serve, daemon=True).start()
    return svc, port


def test_fetch_refuses_paths_outside_work_dir(tmp_path):
    """An unauthenticated ticket naming an arbitrary host file must be
    refused (round-1 advisory: arbitrary file read)."""
    svc, port = _serve(tmp_path)
    client = BallistaClient("127.0.0.1", port)
    try:
        with pytest.raises(RpcError, match="outside work_dir"):
            client.fetch_partition("/etc/passwd")
        # traversal that resolves to a REAL file outside work_dir must be
        # refused by the escape check, not by a no-such-file error
        with pytest.raises(RpcError, match="outside work_dir"):
            client.fetch_partition(str(tmp_path) + "/.." * 16 + "/etc/passwd")
    finally:
        client.close()
        svc.shutdown()


def test_execute_partition_refuses_hostile_job_id(sales_table, tmp_path):
    """job_id is joined into work_dir write paths; a path-shaped id must be
    rejected before any directory is created."""
    from ballista_tpu.engine import ExecutionContext

    svc, port = _serve(tmp_path)
    ctx = ExecutionContext()
    ctx.register_record_batches("sales", sales_table, n_partitions=1)
    from ballista_tpu.logical import col, functions as F

    df = ctx.table("sales").aggregate([], [F.sum(col("amount")).alias("s")])
    physical = ctx.create_physical_plan(df.logical_plan())

    client = BallistaClient("127.0.0.1", port)
    try:
        with pytest.raises(RpcError, match="invalid job id"):
            client.execute_partition("../../evil", 1, [0], physical)
        assert not (tmp_path.parent.parent / "evil").exists()
    finally:
        client.close()
        svc.shutdown()


def test_fetch_streams_multibatch_partition(tmp_path):
    """A multi-batch IPC file arrives batch-by-batch (not one read_all table):
    the stream must preserve batch boundaries."""
    import pyarrow.ipc as ipc

    from ballista_tpu.proto import ballista_pb2 as pb

    piece = tmp_path / "job" / "1" / "0.arrow"
    piece.parent.mkdir(parents=True)
    schema = pa.schema([("x", pa.int64())])
    with ipc.new_file(str(piece), schema) as w:
        for start in range(0, 1000, 100):
            w.write_batch(
                pa.record_batch([pa.array(range(start, start + 100))], schema=schema)
            )

    svc, port = _serve(tmp_path)
    client = BallistaClient("127.0.0.1", port)
    try:
        action = pb.Action()
        action.fetch_partition.path = str(piece)
        batches = list(client.stream_action(action))
        assert len(batches) == 10
        assert all(b.num_rows == 100 for b in batches)
        assert pa.Table.from_batches(batches).column("x").to_pylist() == list(range(1000))
    finally:
        client.close()
        svc.shutdown()


def test_execute_partition_scan_root_allowlist(tmp_path):
    """With data_roots configured, a wire plan scanning a file outside the
    allowlist is refused (the reference executes any deserialized plan —
    rust/executor/src/flight_service.rs:90-192; this rewrite does not).
    A scan under the root still executes."""
    import socket
    import threading

    import pyarrow.parquet as pq

    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.engine import ExecutionContext
    from ballista_tpu.executor.flight_service import BallistaFlightService
    from ballista_tpu.logical import col, functions as F

    allowed = tmp_path / "data"
    allowed.mkdir()
    pq.write_table(pa.table({"x": [1.0, 2.0]}), str(allowed / "ok.parquet"))
    outside = tmp_path / "secret.parquet"
    pq.write_table(pa.table({"x": [9.0]}), str(outside))

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    svc = BallistaFlightService(
        f"grpc://0.0.0.0:{port}",
        str(tmp_path / "work"),
        BallistaConfig({"ballista.executor.data_roots": str(allowed)}),
    )
    threading.Thread(target=svc.serve, daemon=True).start()

    def plan_for(path):
        ctx = ExecutionContext()
        ctx.register_parquet("t", str(path))
        df = ctx.table("t").aggregate([], [F.sum(col("x")).alias("s")])
        return ctx.create_physical_plan(df.logical_plan())

    client = BallistaClient("127.0.0.1", port)
    # inside the allowlist: fine
    results = client.execute_partition("joba", 1, [0], plan_for(allowed / "ok.parquet"))
    assert len(results) == 1
    # outside (e.g. /etc/passwd-shaped exfiltration): refused
    with pytest.raises(Exception, match="outside configured data roots"):
        client.execute_partition("jobb", 1, [0], plan_for(outside))
    # client-supplied per-job settings must NOT widen the allowlist
    with pytest.raises(Exception, match="outside configured data roots"):
        client.execute_partition(
            "jobc", 1, [0], plan_for(outside),
            settings={"ballista.executor.data_roots": ""},
        )
    client.close()
    svc.shutdown()


def test_scan_allowlist_refuses_before_deserialization(tmp_path, monkeypatch):
    """The refusal must happen on the RAW proto: constructing a parquet
    source already reads the file footer, which would hand the peer an
    existence/readability oracle for host paths."""
    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.errors import PlanError
    from ballista_tpu.executor import confine
    from ballista_tpu.proto import ballista_pb2 as pb
    import ballista_tpu.serde.logical as slog

    touched = []
    orig = slog.source_from_proto

    def spy(d):
        touched.append(d.path)
        return orig(d)

    monkeypatch.setattr(slog, "source_from_proto", spy)
    n = pb.PhysicalPlanNode()
    n.scan.scan.source.table_type = "parquet"
    n.scan.scan.source.path = "/etc/passwd"
    with pytest.raises(PlanError, match="outside configured data roots"):
        confine.check_proto_scan_roots(n, [str(tmp_path)])
    assert not touched  # nothing was deserialized, no disk I/O happened


def test_shuffle_reader_local_shortcut_confined_to_own_job(tmp_path):
    """A wire plan naming another job's shuffle directory must not read it
    from local disk; out-of-job locations go through the Flight fetcher
    (which the owning executor confines)."""
    import pyarrow.ipc as ipc

    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.distributed.stages import ShuffleLocation, ShuffleReaderExec
    from ballista_tpu.physical.plan import TaskContext

    schema = pa.schema([pa.field("x", pa.int64())])
    other = tmp_path / "work" / "otherjob" / "1" / "0"
    other.mkdir(parents=True)
    with ipc.new_file(str(other / "0.arrow"), schema) as w:
        w.write_batch(pa.record_batch([pa.array([42])], schema=schema))

    reader = ShuffleReaderExec(
        [ShuffleLocation("e1", "127.0.0.1", 1, str(other))], schema, 1
    )
    fetched = []

    def fetcher(loc, piece):
        fetched.append((loc.path, piece))
        return iter(())

    # same work_dir, DIFFERENT job: local read refused, fetcher used
    ctx = TaskContext(config=BallistaConfig(), work_dir=str(tmp_path / "work"),
                      job_id="myjob", shuffle_fetcher=fetcher)
    assert list(reader.execute(0, ctx)) == []
    assert fetched == [(str(other), 0)]

    # the task's own job directory keeps the local shortcut
    mine = tmp_path / "work" / "myjob" / "1" / "0"
    mine.mkdir(parents=True)
    with ipc.new_file(str(mine / "0.arrow"), schema) as w:
        w.write_batch(pa.record_batch([pa.array([7])], schema=schema))
    reader2 = ShuffleReaderExec(
        [ShuffleLocation("e1", "127.0.0.1", 1, str(mine))], schema, 1
    )
    out = list(reader2.execute(0, ctx))
    assert out and out[0].column(0).to_pylist() == [7]

"""BallistaClient (Flight wrapper) tests: the push-based ExecutePartition
path (ref BallistaClient::execute_partition) and fetch_partition."""

import os

import pyarrow as pa
import pytest

from ballista_tpu.client.flight import BallistaClient
from ballista_tpu.errors import RpcError


def test_prelude_imports():
    import ballista_tpu.prelude as p

    assert p.col("x").name == "x"
    assert callable(p.functions.sum)


def test_execute_and_fetch_partition(sales_table, tmp_path):
    from ballista_tpu.engine import ExecutionContext

    svc, port = _serve(tmp_path)

    # build a plan locally and push it to the executor
    ctx = ExecutionContext()
    ctx.register_record_batches("sales", sales_table, n_partitions=2)
    from ballista_tpu.logical import col, functions as F

    df = ctx.table("sales").aggregate([], [F.sum(col("amount")).alias("s")])
    physical = ctx.create_physical_plan(df.logical_plan())

    client = BallistaClient("127.0.0.1", port)
    results = client.execute_partition("jobf", 1, [0], physical)
    assert len(results) == 1
    path, stats = results[0]
    assert stats.num_rows == 1

    fetched = client.fetch_partition(os.path.join(path, "0.arrow"))
    assert fetched.column("s").to_pylist() == [305.0]
    client.close()
    svc.shutdown()


def _serve(tmp_path):
    import socket
    import threading

    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.executor.flight_service import BallistaFlightService

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    svc = BallistaFlightService(
        f"grpc://0.0.0.0:{port}", str(tmp_path), BallistaConfig()
    )
    threading.Thread(target=svc.serve, daemon=True).start()
    return svc, port


def test_fetch_refuses_paths_outside_work_dir(tmp_path):
    """An unauthenticated ticket naming an arbitrary host file must be
    refused (round-1 advisory: arbitrary file read)."""
    svc, port = _serve(tmp_path)
    client = BallistaClient("127.0.0.1", port)
    try:
        with pytest.raises(RpcError, match="outside work_dir"):
            client.fetch_partition("/etc/passwd")
        # traversal that resolves to a REAL file outside work_dir must be
        # refused by the escape check, not by a no-such-file error
        with pytest.raises(RpcError, match="outside work_dir"):
            client.fetch_partition(str(tmp_path) + "/.." * 16 + "/etc/passwd")
    finally:
        client.close()
        svc.shutdown()


def test_execute_partition_refuses_hostile_job_id(sales_table, tmp_path):
    """job_id is joined into work_dir write paths; a path-shaped id must be
    rejected before any directory is created."""
    from ballista_tpu.engine import ExecutionContext

    svc, port = _serve(tmp_path)
    ctx = ExecutionContext()
    ctx.register_record_batches("sales", sales_table, n_partitions=1)
    from ballista_tpu.logical import col, functions as F

    df = ctx.table("sales").aggregate([], [F.sum(col("amount")).alias("s")])
    physical = ctx.create_physical_plan(df.logical_plan())

    client = BallistaClient("127.0.0.1", port)
    try:
        with pytest.raises(RpcError, match="invalid job id"):
            client.execute_partition("../../evil", 1, [0], physical)
        assert not (tmp_path.parent.parent / "evil").exists()
    finally:
        client.close()
        svc.shutdown()


def test_fetch_streams_multibatch_partition(tmp_path):
    """A multi-batch IPC file arrives batch-by-batch (not one read_all table):
    the stream must preserve batch boundaries."""
    import pyarrow.ipc as ipc

    from ballista_tpu.proto import ballista_pb2 as pb

    piece = tmp_path / "job" / "1" / "0.arrow"
    piece.parent.mkdir(parents=True)
    schema = pa.schema([("x", pa.int64())])
    with ipc.new_file(str(piece), schema) as w:
        for start in range(0, 1000, 100):
            w.write_batch(
                pa.record_batch([pa.array(range(start, start + 100))], schema=schema)
            )

    svc, port = _serve(tmp_path)
    client = BallistaClient("127.0.0.1", port)
    try:
        action = pb.Action()
        action.fetch_partition.path = str(piece)
        batches = list(client.stream_action(action))
        assert len(batches) == 10
        assert all(b.num_rows == 100 for b in batches)
        assert pa.Table.from_batches(batches).column("x").to_pylist() == list(range(1000))
    finally:
        client.close()
        svc.shutdown()

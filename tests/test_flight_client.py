"""BallistaClient (Flight wrapper) tests: the push-based ExecutePartition
path (ref BallistaClient::execute_partition) and fetch_partition."""

import os

import pyarrow as pa
import pytest

from ballista_tpu.client.flight import BallistaClient
from ballista_tpu.errors import RpcError


def test_prelude_imports():
    import ballista_tpu.prelude as p

    assert p.col("x").name == "x"
    assert callable(p.functions.sum)


def test_execute_and_fetch_partition(sales_table, tmp_path):
    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.engine import ExecutionContext
    from ballista_tpu.executor.flight_service import BallistaFlightService
    import threading

    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    svc = BallistaFlightService(
        f"grpc://0.0.0.0:{port}", str(tmp_path), BallistaConfig()
    )
    t = threading.Thread(target=svc.serve, daemon=True)
    t.start()

    # build a plan locally and push it to the executor
    ctx = ExecutionContext()
    ctx.register_record_batches("sales", sales_table, n_partitions=2)
    from ballista_tpu.logical import col, functions as F

    df = ctx.table("sales").aggregate([], [F.sum(col("amount")).alias("s")])
    physical = ctx.create_physical_plan(df.logical_plan())

    client = BallistaClient("127.0.0.1", port)
    results = client.execute_partition("jobf", 1, [0], physical)
    assert len(results) == 1
    path, stats = results[0]
    assert stats.num_rows == 1

    fetched = client.fetch_partition(os.path.join(path, "0.arrow"))
    assert fetched.column("s").to_pylist() == [305.0]
    client.close()
    svc.shutdown()

"""Device join kernel vs host join oracle."""

import numpy as np
import pyarrow as pa
import pytest

from ballista_tpu.config import BallistaConfig
from ballista_tpu.engine import ExecutionContext
from ballista_tpu.ops.join import device_join_indices


def test_device_join_indices_basic():
    build = np.array([10, 3, 7, 1], dtype=np.int64)
    probe = np.array([7, 7, 2, 10, 1], dtype=np.int64)
    build_idx, probe_idx, counts = device_join_indices(build, probe)
    assert counts.tolist() == [1, 1, 0, 1, 1]
    assert build_idx.tolist() == [2, 2, 0, 3]
    assert probe_idx.tolist() == [0, 1, 3, 4]


def test_device_join_expands_duplicates():
    """The retired unique-build-key decline: duplicate build keys expand to
    their full multiplicity, probe-major, build rows in original order."""
    build = np.array([5, 5, 6], dtype=np.int64)
    probe = np.array([5, 6, 5], dtype=np.int64)
    build_idx, probe_idx, counts = device_join_indices(build, probe)
    assert counts.tolist() == [2, 1, 2]
    assert build_idx.tolist() == [0, 1, 2, 0, 1]
    assert probe_idx.tolist() == [0, 0, 1, 2, 2]


def test_device_join_null_probe_keys():
    build = np.array([1, 2, 3], dtype=np.int64)
    probe = np.array([2, -1, 3], dtype=np.int64)  # -1 = null code
    _, probe_idx, counts = device_join_indices(build, probe)
    assert counts.tolist() == [1, 0, 1]
    assert probe_idx.tolist() == [0, 2]


@pytest.mark.parametrize("n", [1000, 5000])
def test_device_join_vs_host_random(n):
    rng = np.random.default_rng(3)
    build = rng.permutation(n * 2)[:n].astype(np.int64)  # unique
    probe = rng.integers(0, n * 2, n * 3).astype(np.int64)
    build_idx, probe_idx, counts = device_join_indices(build, probe)
    lookup = {int(k): i for i, k in enumerate(build)}
    hits = {int(p): int(b) for b, p in zip(build_idx, probe_idx)}
    for j in range(len(probe)):
        want = lookup.get(int(probe[j]), None)
        assert counts[j] == (0 if want is None else 1)
        if want is not None:
            assert hits[j] == want


def _tpch_join_sql():
    return (
        "select o_orderkey, c_name, o_totalprice from orders, customer "
        "where o_custkey = c_custkey and o_totalprice > 100000 "
        "order by o_totalprice desc limit 10"
    )


def test_tpu_backend_join_matches_cpu(tmp_path_factory):
    from benchmarks.tpch.datagen import generate, register_all

    d = str(tmp_path_factory.mktemp("tpch_join"))
    generate(d, sf=0.002, parts=2)
    out = {}
    for backend in ("cpu", "tpu"):
        ctx = ExecutionContext(BallistaConfig({"ballista.executor.backend": backend}))
        register_all(ctx, d)
        out[backend] = ctx.sql(_tpch_join_sql()).collect().to_pylist()
    assert out["cpu"] == out["tpu"]


# ---------------------------------------------------------------------------
# membership counting (ISSUE 7 satellite: the q13/q22 device path)
# ---------------------------------------------------------------------------


def test_device_membership_counts_matches_host_oracle():
    """The counts-only plane: per-probe run-lengths bit-equal to the host
    join_indices counts, nulls (code -1) on both sides included."""
    from ballista_tpu.ops.join import device_membership_counts
    from ballista_tpu.physical.joinutil import join_indices

    rng = np.random.default_rng(11)
    build = rng.integers(0, 40, 300).astype(np.int64)
    build[rng.integers(0, 300, 20)] = -1  # null build keys never match
    probe = rng.integers(0, 60, 500).astype(np.int64)
    probe[rng.integers(0, 500, 30)] = -1
    counts = device_membership_counts(build, probe)
    assert counts is not None
    # host oracle counts via the inner join's probe_idx multiplicities
    _b, p = join_indices(build, probe, "inner")
    want = np.bincount(p, minlength=len(probe)) if len(p) else np.zeros(len(probe), int)
    assert counts.tolist() == want.tolist()
    assert all(counts[probe < 0] == 0)


def _both_backends(tables, sql):
    out = {}
    for backend in ("cpu", "tpu"):
        ctx = ExecutionContext(BallistaConfig({"ballista.executor.backend": backend}))
        for name, t in tables.items():
            ctx.register_record_batches(name, t, n_partitions=1)
        out[backend] = ctx.sql(sql).collect().to_pylist()
    return out


def _count_join_tables(with_nulls=False):
    rng = np.random.default_rng(23)
    n_c, n_o = 200, 1500
    cust = pa.table({
        "c_id": pa.array(np.arange(n_c), type=pa.int64()),
        "c_grp": pa.array(rng.integers(0, 9, n_c), type=pa.int64()),
    })
    oid = rng.integers(0, 5000, n_o)
    okey = rng.integers(0, int(n_c * 1.3), n_o)  # some point past customers
    orders = {
        "o_id": pa.array(oid, type=pa.int64()),
        "o_cust": pa.array(okey, type=pa.int64()),
    }
    if with_nulls:
        # nulls in the COUNTED column (COUNT must skip them) and in the
        # join key (never matches)
        null_at = rng.random(n_o) < 0.15
        orders["o_id"] = pa.array(
            [None if m else int(v) for v, m in zip(oid, null_at)],
            type=pa.int64(),
        )
        key_null = rng.random(n_o) < 0.1
        orders["o_cust"] = pa.array(
            [None if m else int(v) for v, m in zip(okey, key_null)],
            type=pa.int64(),
        )
    return {"cust": cust, "orders": pa.table(orders)}


@pytest.mark.parametrize("with_nulls", [False, True])
def test_count_over_left_join_device_matches_cpu(with_nulls):
    """q13's shape: COUNT(right column) grouped by left keys over a LEFT
    join routes through the per-probe counts plane — tpu == cpu
    bit-equality (counts are exact ints), including NULL counted values
    and NULL join keys."""
    from ballista_tpu.utils import tracing

    sql = (
        "select c_grp, cnt, count(*) as dist from ("
        "  select c_id, c_grp, count(o_id) as cnt from cust "
        "  left outer join orders on c_id = o_cust group by c_id, c_grp"
        ") sub group by c_grp, cnt order by c_grp, cnt"
    )
    tracing.reset()
    out = _both_backends(_count_join_tables(with_nulls), sql)
    assert out["cpu"] == out["tpu"]
    assert tracing.counters().get("device.count_join", 0) >= 1


def test_anti_join_membership_device_matches_cpu():
    """q22's NOT EXISTS: the ANTI join keeps rows off counts == 0 on
    device, bit-identical to the host anti_right selection."""
    from ballista_tpu.ops.runtime import join_path_stats

    tables = _count_join_tables()
    sql = (
        "select c_grp, count(*) as n from cust where not exists ("
        "  select * from orders where o_cust = c_id"
        ") group by c_grp order by c_grp"
    )
    join_path_stats(reset=True)
    out = _both_backends(tables, sql)
    assert out["cpu"] == out["tpu"]
    assert join_path_stats(reset=True).get("paths", {}).get("device", 0) >= 1


def test_q13_q22_device_engaged_on_tpch(tmp_path_factory):
    """The ROADMAP carry-over struck for real: q13 and q22 run their
    membership counting on the device path (counter-asserted) and stay
    bit-identical to the cpu backend on real TPC-H data."""
    import pathlib

    from benchmarks.tpch.datagen import generate, register_all
    from ballista_tpu.utils import tracing

    d = str(tmp_path_factory.mktemp("tpch_q13"))
    generate(d, sf=0.002, parts=2)
    qdir = pathlib.Path(__file__).parent.parent / "benchmarks" / "tpch" / "queries"
    out = {}
    for backend in ("cpu", "tpu"):
        ctx = ExecutionContext(BallistaConfig({"ballista.executor.backend": backend}))
        register_all(ctx, d)
        tracing.reset()
        out[backend] = {
            q: ctx.sql((qdir / f"{q}.sql").read_text()).collect().to_pylist()
            for q in ("q13", "q22")
        }
        if backend == "tpu":
            assert tracing.counters().get("device.count_join", 0) >= 1
    # counts are ints and q22's sum is exact over these rows: bit-equality
    assert out["cpu"]["q13"] == out["tpu"]["q13"]
    assert out["cpu"]["q22"] == out["tpu"]["q22"]

"""Device join kernel vs host join oracle."""

import numpy as np
import pyarrow as pa
import pytest

from ballista_tpu.config import BallistaConfig
from ballista_tpu.engine import ExecutionContext
from ballista_tpu.ops.join import device_join_indices


def test_device_join_indices_basic():
    build = np.array([10, 3, 7, 1], dtype=np.int64)
    probe = np.array([7, 7, 2, 10, 1], dtype=np.int64)
    build_idx, probe_idx, counts = device_join_indices(build, probe)
    assert counts.tolist() == [1, 1, 0, 1, 1]
    assert build_idx.tolist() == [2, 2, 0, 3]
    assert probe_idx.tolist() == [0, 1, 3, 4]


def test_device_join_expands_duplicates():
    """The retired unique-build-key decline: duplicate build keys expand to
    their full multiplicity, probe-major, build rows in original order."""
    build = np.array([5, 5, 6], dtype=np.int64)
    probe = np.array([5, 6, 5], dtype=np.int64)
    build_idx, probe_idx, counts = device_join_indices(build, probe)
    assert counts.tolist() == [2, 1, 2]
    assert build_idx.tolist() == [0, 1, 2, 0, 1]
    assert probe_idx.tolist() == [0, 0, 1, 2, 2]


def test_device_join_null_probe_keys():
    build = np.array([1, 2, 3], dtype=np.int64)
    probe = np.array([2, -1, 3], dtype=np.int64)  # -1 = null code
    _, probe_idx, counts = device_join_indices(build, probe)
    assert counts.tolist() == [1, 0, 1]
    assert probe_idx.tolist() == [0, 2]


@pytest.mark.parametrize("n", [1000, 5000])
def test_device_join_vs_host_random(n):
    rng = np.random.default_rng(3)
    build = rng.permutation(n * 2)[:n].astype(np.int64)  # unique
    probe = rng.integers(0, n * 2, n * 3).astype(np.int64)
    build_idx, probe_idx, counts = device_join_indices(build, probe)
    lookup = {int(k): i for i, k in enumerate(build)}
    hits = {int(p): int(b) for b, p in zip(build_idx, probe_idx)}
    for j in range(len(probe)):
        want = lookup.get(int(probe[j]), None)
        assert counts[j] == (0 if want is None else 1)
        if want is not None:
            assert hits[j] == want


def _tpch_join_sql():
    return (
        "select o_orderkey, c_name, o_totalprice from orders, customer "
        "where o_custkey = c_custkey and o_totalprice > 100000 "
        "order by o_totalprice desc limit 10"
    )


def test_tpu_backend_join_matches_cpu(tmp_path_factory):
    from benchmarks.tpch.datagen import generate, register_all

    d = str(tmp_path_factory.mktemp("tpch_join"))
    generate(d, sf=0.002, parts=2)
    out = {}
    for backend in ("cpu", "tpu"):
        ctx = ExecutionContext(BallistaConfig({"ballista.executor.backend": backend}))
        register_all(ctx, d)
        out[backend] = ctx.sql(_tpch_join_sql()).collect().to_pylist()
    assert out["cpu"] == out["tpu"]

"""Regression tests for code-review findings on the engine core."""

import numpy as np
import pyarrow as pa
import pytest

from ballista_tpu.engine import ExecutionContext
from ballista_tpu.errors import PlanError
from ballista_tpu.logical import col, functions as F
from ballista_tpu.logical.expr import AggregateExpr
from ballista_tpu.physical.joinutil import combined_key_codes, join_indices


def test_wide_int64_keys_no_overflow():
    # 64-bit id-style keys spanning > 2^32: packing must not wrap
    k = 2**32
    left = pa.array([k, 2 * k, 3 * k], type=pa.int64())
    right = pa.array([2 * k, 5], type=pa.int64())
    lc, rc = combined_key_codes([left], [right])
    li, ri = join_indices(lc, rc, "inner")
    assert list(zip(li.tolist(), ri.tolist())) == [(1, 0)]


def test_composite_wide_keys_no_overflow():
    k = 2**31
    left = [pa.array([k, 2 * k], type=pa.int64()), pa.array([3 * k, 4 * k], type=pa.int64())]
    right = [pa.array([2 * k, k], type=pa.int64()), pa.array([4 * k, 9], type=pa.int64())]
    lc, rc = combined_key_codes(left, right)
    li, ri = join_indices(lc, rc, "inner")
    assert list(zip(li.tolist(), ri.tolist())) == [(1, 0)]


def test_cross_join_duplicate_names_rejected():
    ctx = ExecutionContext()
    ctx.register_record_batches("l", pa.table({"k": [1, 2]}))
    ctx.register_record_batches("r", pa.table({"k": [3]}))
    from ballista_tpu.logical.plan import CrossJoin

    with pytest.raises(PlanError, match="duplicate field"):
        CrossJoin(
            ctx.table("l").logical_plan(), ctx.table("r").logical_plan()
        )


def test_cross_join_with_aliases():
    ctx = ExecutionContext()
    ctx.register_record_batches("l", pa.table({"k": [1, 2]}))
    ctx.register_record_batches("r", pa.table({"k": [3]}))
    from ballista_tpu.logical.plan import CrossJoin
    from ballista_tpu.logical.builder import LogicalPlanBuilder

    plan = CrossJoin(
        ctx.table("l").alias("a").logical_plan(),
        ctx.table("r").alias("b").logical_plan(),
    )
    out = ctx.collect(plan)
    assert out.column_names == ["a.k", "b.k"]
    assert sorted(out.column("a.k").to_pylist()) == [1, 2]
    assert out.column("b.k").to_pylist() == [3, 3]


def test_distinct_over_alias():
    ctx = ExecutionContext()
    ctx.register_record_batches("t", pa.table({"a": [1, 1, 2]}))
    out = ctx.table("t").alias("x").distinct().collect()
    assert out.column_names == ["x.a"]
    assert sorted(out.column("x.a").to_pylist()) == [1, 2]


def test_sum_distinct_rejected_at_plan_time():
    ctx = ExecutionContext()
    ctx.register_record_batches("t", pa.table({"a": [1, 1, 2], "g": [1, 1, 2]}))
    df = ctx.table("t").aggregate(
        [col("g")], [AggregateExpr("sum", col("a"), distinct=True).alias("s")]
    )
    with pytest.raises(PlanError, match="DISTINCT is only supported for COUNT"):
        df.collect()


def test_count_distinct():
    ctx = ExecutionContext()
    ctx.register_record_batches(
        "t", pa.table({"g": [1, 1, 1, 2], "a": [5, 5, 6, 7]}), n_partitions=2
    )
    out = (
        ctx.table("t")
        .aggregate([col("g")], [F.count(col("a"), distinct=True).alias("c")])
        .sort(col("g").sort())
        .collect()
    )
    assert out.column("c").to_pylist() == [2, 1]


def test_single_partition_uses_single_mode():
    ctx = ExecutionContext()
    ctx.register_record_batches("t", pa.table({"g": [1, 2], "a": [3, 4]}))
    df = ctx.table("t").aggregate([col("g")], [F.sum(col("a")).alias("s")])
    physical = ctx.create_physical_plan(df.logical_plan())
    from ballista_tpu.physical.aggregate import AggregateMode, HashAggregateExec

    assert isinstance(physical, HashAggregateExec)
    assert physical.mode == AggregateMode.SINGLE


def test_tpu_backend_falls_back_cleanly():
    from ballista_tpu.config import BallistaConfig

    ctx = ExecutionContext(BallistaConfig({"ballista.executor.backend": "tpu"}))
    ctx.register_record_batches("t", pa.table({"a": [1, 2, 3]}))
    from ballista_tpu.logical import lit

    out = ctx.table("t").filter(col("a") > lit(1)).select(col("a")).collect()
    assert out.column("a").to_pylist() == [2, 3]
